"""Trace-driven fleet simulator: the control plane on a virtual clock.

A discrete-event harness that runs the REAL serving control plane —
:class:`~tfmesos_tpu.fleet.admission.AdmissionController` WFQ queues,
:class:`~tfmesos_tpu.fleet.router.Router` (picks, retries, breakers,
budget, deadlines, disagg orchestration, migration re-placement),
:class:`~tfmesos_tpu.fleet.containment.BreakerBoard` /
:class:`~tfmesos_tpu.fleet.containment.RetryBudget`,
:class:`~tfmesos_tpu.fleet.registry.ReplicaRegistry` (the actual table,
fences and sweeps included), and the real
:class:`~tfmesos_tpu.fleet.autoscaler.FleetAutoscaler` feedback loop —
against SIMULATED replicas: per-replica state machines parameterized by
a latency model, capacity, KV headroom, and a failure script, instead
of processes.  TF-Replicator's separate-policy-from-mechanism argument
(PAPERS.md) is the design warrant: the mechanisms are jax-free and
clock-injectable, so their policies can be evaluated against recorded
or synthesized workloads in seconds of CPU — 1000-replica fleets,
millions of requests — instead of minutes of live wall-clock.

How time works (the whole trick):

* One :class:`VirtualClock` is injected as the ``clock`` of the
  registry, admission controller, router (and its breaker board), and
  autoscaler — the same parameter production binds to
  ``time.monotonic``.  Nothing on the control plane reads real time.
* A single event heap orders the future: request arrivals, call
  completions, heartbeats, registry sweeps, autoscaler ticks.  The
  engine pops events in time order and advances the clock to each.
* The control-plane code is SYNCHRONOUS (the router blocks in
  ``link.call``), so blocking points run on cooperative worker fibers:
  real threads scheduled strictly one-at-a-time by the engine.  A
  fiber entering a virtual wait (a call in flight, a retry backoff)
  parks; the engine wakes it at the event that resolves the wait.  At
  most one thread runs at any instant — execution is deterministic,
  seeded, and involves ZERO real sleeping (the router's ``sleep`` is
  the engine's virtual one; tier-1 asserts no ``time.sleep`` fires).
* When a call's completion would be the next event anyway, the engine
  advances the clock directly and returns in-line (the classic DES
  no-intervening-event shortcut) — no thread handoff on the fast path.

Workloads come from :mod:`tfmesos_tpu.fleet.workload`: a seeded
synthesizer, or replay of a recorded ``tfserve trace --json`` export.
Scenarios (``SCENARIOS``) package fleet + workload + timeline;
``tfserve simulate`` runs them by name, and ``--sweep
breaker.latency_factor=2,4,8`` runs one per value for policy tuning
(:func:`apply_override` addresses every promoted policy constant by
path).  The ``soak-replay`` scenario is the FIDELITY GATE: it replays
the seeded chaos timeline of ``bench_fleet_soak`` (gray-slow replica,
hard kill + autoscaler self-heal, link sever, blue-green rollout) and
must reproduce its qualitative outcomes — breaker isolation of the
slow replica while heartbeat-alive, zero lost requests, retry
amplification <= 1.5 — asserted in tier-1 so policy regressions fail
CI deterministically (docs/SIMULATOR.md).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import (AdmissionController,
                                         DEFAULT_MAX_QUEUE,
                                         DeadlineExceeded, Overloaded,
                                         PriorityClass, RateLimited)
from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from tfmesos_tpu.fleet.catalog import (POOL, ModelCatalog, ModelSpec,
                                       ModelTrader, TraderConfig,
                                       model_key, split_key)
from tfmesos_tpu.fleet.client import CallTimeout, ConnectionLost
from tfmesos_tpu.fleet.containment import BreakerConfig, RetryBudget
from tfmesos_tpu.fleet.kvtier import rendezvous_order
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.registry import (DECODE, PREFILL, UNIFIED, WARMING,
                                        ReplicaRegistry)
from tfmesos_tpu.fleet.router import Router
from tfmesos_tpu.fleet.workload import (DiurnalWorkload, Request,
                                        SyntheticWorkload)
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["VirtualClock", "SimEngine", "ReplicaModel", "SimReplica",
           "SimConfig", "FleetSim", "apply_override", "parse_sweep",
           "run_scenario", "run_sweep", "SCENARIOS"]


# -- the virtual clock & engine ----------------------------------------------


class VirtualClock:
    """Callable monotone virtual time in seconds — drop-in for the
    ``clock=time.monotonic`` parameter everywhere it exists."""

    __slots__ = ("now",)

    def __init__(self, start: float = 0.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now


class _FiberStop(BaseException):
    """Raised inside a parked fiber at teardown; BaseException so no
    control-plane except-clause can swallow it."""


class _Baton:
    """A binary handoff built on a raw ``threading.Lock`` (a C futex —
    several times cheaper per handoff than ``threading.Event``, whose
    wait path is a Python-level Condition).  Strict baton-passing
    guarantees at most one ``signal`` precedes each ``wait``."""

    __slots__ = ("_lk",)

    def __init__(self):
        self._lk = threading.Lock()
        self._lk.acquire()

    def wait(self) -> None:
        self._lk.acquire()

    def signal(self) -> None:
        self._lk.release()


class _Fiber:
    """One cooperative worker: a real thread that runs only when the
    engine hands it the baton and parks at every virtual wait."""

    __slots__ = ("name", "baton", "payload", "exc", "done", "thread",
                 "body")

    def __init__(self, engine: "SimEngine", body: Callable[[], None],
                 name: str):
        self.name = name
        self.baton = _Baton()
        self.payload: Any = None
        self.exc: Optional[BaseException] = None
        self.done = False
        self.body = body
        self.thread = threading.Thread(target=self._main, args=(engine,),
                                       name=name, daemon=True)

    def _main(self, engine: "SimEngine") -> None:
        self.baton.wait()
        try:
            if self.exc is None:
                self.body()
        except _FiberStop:
            pass
        except BaseException as e:  # noqa: BLE001 - surfaced to engine
            engine._crash = e
        finally:
            self.done = True
            engine._engine_baton.signal()


class SimEngine:
    """Event heap + virtual clock + cooperative fiber scheduler.

    Strict baton-passing: the engine thread and at most ONE fiber are
    ever runnable, and only one of them at a time — the handoff is two
    Event signals, so simulation is deterministic (seeded rng, ordered
    heap) and costs ~10us per virtual block, zero on the fast path.
    """

    def __init__(self, seed: int = 0):
        self.clock = VirtualClock()
        self.rng = random.Random(seed)
        self.events = 0
        self._heap: List[tuple] = []
        self._seq = 0
        self._engine_baton = _Baton()
        self._current: Optional[_Fiber] = None
        self._crash: Optional[BaseException] = None
        self._fibers: List[_Fiber] = []

    # -- scheduling (single-threaded by protocol) --------------------------

    def at(self, t: float, fn: Callable[[], None]) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (t, self._seq, fn))

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self.clock.now + dt, fn)

    # -- engine-context primitives -----------------------------------------

    def spawn(self, body: Callable[[], None],
              name: str = "sim-fiber") -> _Fiber:
        """Create a fiber and run it until its first park (so a worker
        reaches its idle wait before any event fires)."""
        f = _Fiber(self, body, name)
        self._fibers.append(f)
        f.thread.start()
        self._resume(f)
        return f

    def _resume(self, fiber: _Fiber, payload: Any = None,
                exc: Optional[BaseException] = None) -> None:
        """Hand the baton to ``fiber`` (delivering ``payload`` or
        raising ``exc`` from its park) and block until it parks again
        or finishes."""
        prev = self._current
        fiber.payload, fiber.exc = payload, exc
        self._current = fiber
        fiber.baton.signal()
        self._engine_baton.wait()
        self._current = prev
        if self._crash is not None:
            crash, self._crash = self._crash, None
            raise crash

    def run(self, until: Optional[float] = None,
            stop: Optional[Callable[[], bool]] = None) -> None:
        """Pop events in time order until the heap empties, ``until``
        virtual seconds pass, or ``stop()`` answers True (checked
        between events)."""
        heap = self._heap
        clock = self.clock
        while heap:
            if stop is not None and stop():
                return
            t = heap[0][0]
            if until is not None and t > until:
                break
            _, _, fn = heapq.heappop(heap)
            if t > clock.now:
                clock.now = t
            self.events += 1
            fn()
        if until is not None and clock.now < until:
            clock.now = until

    def stop_fibers(self) -> None:
        """Unwind every parked fiber with :class:`_FiberStop`."""
        for f in self._fibers:
            if not f.done:
                self._resume(f, exc=_FiberStop())
        for f in self._fibers:
            f.thread.join(timeout=2.0)
        self._fibers = []

    # -- fiber-context primitives ------------------------------------------

    def park(self) -> Any:
        """Block the current fiber until the engine resumes it; returns
        the resume payload or raises the resume exception."""
        me = self._current
        self._engine_baton.signal()
        me.baton.wait()
        if me.exc is not None:
            exc, me.exc = me.exc, None
            raise exc
        return me.payload

    def sleep(self, dt: float) -> None:
        """Virtual sleep — what the router's injected ``sleep`` binds
        to; no real time passes."""
        if dt <= 0:
            return
        me = self._current
        fired = [False]

        def wake() -> None:
            if not fired[0]:
                fired[0] = True
                self._resume(me)

        self.at(self.clock.now + dt, wake)
        self.park()

    def fast_forward(self, t: float) -> bool:
        """If nothing is scheduled before ``t``, jump the clock there
        and return True — the caller may resolve its wait in-line
        without a park/resume round trip.  Correct because the strict
        baton protocol guarantees no other fiber is runnable."""
        if self._heap and self._heap[0][0] < t:
            return False
        if t > self.clock.now:
            self.clock.now = t
        self.events += 1
        return True


# -- simulated replicas ------------------------------------------------------


@dataclasses.dataclass
class ReplicaModel:
    """A replica's latency model: TTFT is ``prefill_base_ms +
    prefill_ms_per_token * prompt_len``, the decode tail adds
    ``decode_ms_per_token * new_tokens``; ``jitter`` is a lognormal
    sigma applied multiplicatively (0 = deterministic).  Replay fits
    these from recorded traces (:func:`~tfmesos_tpu.fleet.workload.
    fit_replica_model`).  ``kv_bytes_per_token`` sizes the raw-frame
    KV artifacts the sim's drain migration and session park/resume
    carry (per cached position; the tiny CI model's pages work out to
    ~0.5 KB/token, flagship configs far more)."""

    prefill_base_ms: float = 4.0
    prefill_ms_per_token: float = 0.05
    decode_ms_per_token: float = 2.0
    jitter: float = 0.0
    kv_bytes_per_token: float = 512.0

    def service_s(self, prompt_len: int, new_tokens: int,
                  rng: random.Random) -> Tuple[float, float]:
        """``(ttft_s, total_s)`` for one request."""
        ttft = self.prefill_base_ms + self.prefill_ms_per_token * prompt_len
        total = ttft + self.decode_ms_per_token * new_tokens
        if self.jitter > 0:
            m = rng.lognormvariate(0.0, self.jitter)
            ttft *= m
            total *= m
        return ttft / 1000.0, total / 1000.0


def gang_model(base: ReplicaModel, size: int,
               efficiency: float) -> ReplicaModel:
    """The latency model of a GANG replica: N members SPMD-execute
    each batch, so per-token compute divides by the slice's effective
    speedup (``size × efficiency`` — collectives eat the rest); the
    per-request base overhead and the whole-artifact KV bytes do not
    shrink (the gang's sharded export parks as one artifact)."""
    if size <= 1:
        return base
    speed = max(1.0, size * efficiency)
    return dataclasses.replace(
        base,
        prefill_ms_per_token=base.prefill_ms_per_token / speed,
        decode_ms_per_token=base.decode_ms_per_token / speed)


class SimReplica:
    """One simulated replica: a ``capacity``-server FIFO queue over a
    latency model, plus the failure-script knobs the scenarios twist
    (``slow_factor`` = the gray failure, ``error_rate`` = transient
    internal errors, ``sever_next`` = one-shot link severs,
    ``down`` = a hard kill: beats stop, pending calls fail)."""

    __slots__ = ("addr", "role", "capacity", "model", "weights_version",
                 "gen", "node", "warm_until", "down", "removed",
                 "migrating", "slow_factor", "error_rate", "sever_next",
                 "drop_beats", "kv_pages", "served", "busy_s",
                 "model_id", "pool", "gang_size", "gang_live",
                 "_servers", "_inflight", "_pending")

    def __init__(self, addr: str, role: str = UNIFIED, capacity: int = 4,
                 model: Optional[ReplicaModel] = None,
                 weights_version: str = "v1", gen: int = 0,
                 node: str = "", warm_until: float = 0.0,
                 kv_pages: int = 64, model_id: str = "",
                 pool: bool = False, gang_size: int = 1):
        self.addr = addr
        self.role = role
        self.capacity = int(capacity)
        self.model = model or ReplicaModel()
        self.weights_version = weights_version
        self.gen = int(gen)
        self.node = node
        self.warm_until = float(warm_until)
        self.down = False
        self.removed = False
        self.migrating = False
        self.slow_factor = 1.0
        self.error_rate = 0.0
        self.sever_next = 0
        self.drop_beats = False
        self.kv_pages = int(kv_pages)
        self.served = 0
        # Slot-seconds actually spent serving (the utilization gauge's
        # numerator; deadline cancels shrink it via release_to).
        self.busy_s = 0.0
        # Model catalog: the catalog model this replica serves, and
        # warm-pool membership (undedicated; adoption flips both).
        self.model_id = model_id
        self.pool = bool(pool)
        # Gang replicas: >1 means this sim replica stands for a whole
        # N-member pod-slice gang (one routable leader); its beats
        # carry the gang field the real registry parses.
        self.gang_size = max(1, int(gang_size))
        self.gang_live = self.gang_size
        self._servers = [0.0] * self.capacity     # per-slot free-at
        self._inflight: List[float] = []          # finish times
        self._pending: List[list] = []            # live call records

    def outstanding(self, now: float) -> int:
        fl = self._inflight
        while fl and fl[0] <= now:
            heapq.heappop(fl)
        return len(fl)

    def occupy(self, now: float, service_s: float) -> Tuple[float, float]:
        """FIFO ``capacity``-server queueing: the request starts when
        the earliest slot frees, finishes ``service_s`` later.
        Returns ``(start, finish)``."""
        free = heapq.heappop(self._servers)
        start = max(now, free)
        finish = start + service_s
        heapq.heappush(self._servers, finish)
        heapq.heappush(self._inflight, finish)
        self.busy_s += service_s
        return start, finish

    def release_to(self, finish: float, t: float) -> None:
        """Shrink the occupation that ends at ``finish`` (the value
        :meth:`occupy` just returned) to end at ``t`` instead — an
        in-batcher deadline cancel frees THAT row early, never some
        other in-flight request's slot."""
        shrunk = False
        for heap in (self._servers, self._inflight):
            try:
                heap.remove(finish)
            except ValueError:
                continue
            heapq.heapify(heap)
            heapq.heappush(heap, t)
            shrunk = True
        if shrunk:
            self.busy_s -= max(0.0, finish - t)


# -- the virtual transport ---------------------------------------------------


class _SimLink:
    """MuxConnection-shaped handle the router holds per replica: the
    ``outstanding`` property is its p2c load signal, ``call`` /
    ``call_raw`` resolve through the transport's event heap."""

    __slots__ = ("_hub", "addr", "closed", "_outstanding")

    def __init__(self, hub: "SimTransport", addr: str):
        self._hub = hub
        self.addr = addr
        self.closed = False
        self._outstanding = 0

    @property
    def outstanding(self) -> int:
        return self._outstanding

    def call(self, msg: Dict[str, Any],
             timeout: Optional[float] = None) -> Any:
        return self._hub.call(self, msg, timeout)

    def call_raw(self, meta: Dict[str, Any], body,
                 timeout: Optional[float] = None) -> Any:
        return self._hub.call(self, meta, timeout)

    def close(self) -> None:
        self.closed = True


_EMPTY_TOKENS: tuple = ()


class SimTransport:
    """The fleet's virtual data plane: the router's ``link_factory``.
    Calls compute their reply time from the target replica's queueing
    model + failure script, then either fast-forward (no earlier
    event) or park the calling fiber until the reply event."""

    def __init__(self, engine: SimEngine):
        self.engine = engine
        self.replicas: Dict[str, SimReplica] = {}
        # The sim's KV-tier model (docs/SERVING.md "KV tiering &
        # sessions"): one HOST-SHARED session tier (the disk-dir
        # deployment — replicas of the host resume each other's parked
        # sessions, and a replica death does not lose it), mapping
        # session id -> (covered tokens, weights_version).  A resume
        # only counts when the versions match — the rollout fence.
        self.session_tier: Dict[str, Tuple[int, str, Any]] = {}
        self.session_stats = {"hits": 0, "misses": 0, "park": 0,
                              "resume": 0, "version_miss": 0,
                              "cross_host_miss": 0,
                              "host_loss_miss": 0, "forwarded": 0,
                              "ttft_hit_ms": 0.0, "ttft_cold_ms": 0.0}
        # Cross-host placement knob (gang-parked sharded sessions):
        # the probability a parked artifact resumes on a replica OTHER
        # than its parker — 1.0 is the host-shared disk tier (today's
        # behavior, everything resumable), lower models fleets whose
        # gang artifacts live host-local and a cross-host landing
        # re-prefills cold.
        self.cross_host_resume = 1.0
        # Cross-host KV fabric placement (docs/SERVING.md "Cross-host
        # KV fabric"): 0 keeps the host-shared tier above (a kill
        # loses nothing), K >= 1 switches to per-host tiers with
        # K-way rendezvous-placed parking — an artifact lives on
        # exactly K copy hosts (the real fabric's placement function,
        # so the sim prices the same copy sets the fleet would pick),
        # a resume landing off every copy host forwards the bytes for
        # ``kv_forward_ms`` of TTFT, and a kill loses only sessions
        # whose EVERY copy host died (``host_loss_miss``).
        self.kv_replication = 0
        self.kv_forward_ms = 2.0
        # Copy-placement policy for K-way parking: "rendezvous" is the
        # pure hash ranking, "loaded" stable-sorts that ranking by each
        # candidate's coarse tier occupancy (copies held / kv_pages,
        # quantized to 5 buckets — KVFabric._order's exact rule) so hot
        # tiers shed new copies while near-empty ones keep their hash
        # affinity.
        self.kv_placement = "rendezvous"
        self._tier_load: Dict[str, int] = {}

    def _place(self, sid: str, parker: str) -> Tuple[str, ...]:
        """Pick the K-way copy set for a parked session: the parker
        plus the first K-1 peers under the configured placement."""
        peers = [a for a, h in sorted(self.replicas.items())
                 if not h.down and not h.removed and a != parker]
        ranked = rendezvous_order(sid, peers)
        if self.kv_placement == "loaded":
            load = self._tier_load
            ranked = sorted(
                ranked,
                key=lambda a: min(4, int(
                    4 * load.get(a, 0)
                    / max(1, self.replicas[a].kv_pages))))
        return (parker,) + tuple(
            ranked[:max(0, self.kv_replication - 1)])

    def link(self, addr: str) -> _SimLink:
        rep = self.replicas.get(addr)
        if rep is None or rep.down:
            raise ConnectionLost(f"dial refused: {addr}")
        return _SimLink(self, addr)

    def fail_pending(self, rep: SimReplica,
                     exc_factory=ConnectionLost) -> None:
        """A dying replica fails every in-flight call NOW (the mux
        link's EOF behavior)."""
        pending, rep._pending = rep._pending, []
        for rec in pending:
            if not rec[0]:
                rec[0] = True
                self.engine._resume(rec[1], None,
                                    exc_factory(f"{rep.addr} died "
                                                f"mid-request"))

    def suspend_pending(self, rep: SimReplica) -> None:
        """Drain migration: every in-flight generate answers
        ``suspended`` carrying a RAW-FRAME KV artifact sized from the
        replica model (``kv_bytes_per_token`` × the positions decoded
        so far) — the router re-places it on a same-version survivor
        through its real ``_resume_elsewhere`` path, exactly like a
        live replica's export (PR 11 carried only the requeue-marker
        re-run path).  Calls with no generate shape (control ops)
        still answer the plain requeue marker.  The replica's rows
        free immediately either way."""
        now = self.engine.clock.now
        rep._servers = [now] * rep.capacity
        rep._inflight = []
        pending, rep._pending = rep._pending, []
        for rec in pending:
            if rec[0]:
                continue
            rec[0] = True
            msg = rec[2] if len(rec) > 2 else None
            if isinstance(msg, dict) and msg.get("op") == "generate":
                prompt = msg.get("prompt")
                plen = len(prompt) if prompt is not None else 0
                want = int(msg.get("max_new_tokens") or 1)
                done = max(1, want // 2)    # suspended mid-stream
                body = bytes(min(64 << 20, int(
                    (plen + done) * rep.model.kv_bytes_per_token)))
                meta = {"op": "suspended", "gen": rep.gen,
                        "weights_version": rep.weights_version,
                        "resumed_tokens": done}
                self.engine._resume(rec[1], wire.RawFrame(meta, body),
                                    None)
            else:
                self.engine._resume(rec[1], {"op": "suspended"}, None)

    def call(self, link: _SimLink, msg: Dict[str, Any],
             timeout: Optional[float]) -> Any:
        eng = self.engine
        now = eng.clock.now
        rep = self.replicas.get(link.addr)
        if link.closed or rep is None or rep.down or rep.removed:
            raise ConnectionLost(f"{link.addr} unreachable")
        if rep.sever_next > 0:
            rep.sever_next -= 1
            raise ConnectionLost(f"{link.addr} link severed (scripted)")
        if rep.migrating:
            return {"op": "suspended"}      # requeue marker: re-run
        op = msg.get("op")
        prompt = msg.get("prompt")
        prompt_len = len(prompt) if prompt is not None else 0
        new_tokens = int(msg.get("max_new_tokens") or 1)
        rng = eng.rng
        # Session tier (KV tiering & sessions): a session-labeled
        # generate whose conversation is parked in the host tier
        # prefills only the new TAIL — the parked coverage's positions
        # import instead of recomputing.  Version mismatch (a parked
        # v1 artifact after a v2 rollout) is a counted miss: the turn
        # re-prefills cold, never stale KV.
        sid = msg.get("session")
        sid = sid if isinstance(sid, str) and sid else None
        session_hit = False
        session_forward = False
        eff_prompt = prompt_len
        if sid is not None and op == "generate":
            st = self.session_stats
            ent = self.session_tier.get(sid)
            if ent is not None and 0 < ent[0] < prompt_len:
                holders = ent[2] if len(ent) > 2 else ()
                if isinstance(holders, str):
                    holders = (holders,) if holders else ()
                alive = tuple(
                    a for a in holders
                    if a in self.replicas and not self.replicas[a].down
                    and not self.replicas[a].removed)
                if self.kv_replication >= 1 and not alive:
                    # Fabric placement model: every copy host died
                    # with the artifact — K-way parking was the only
                    # defense, and K was too small.
                    st["host_loss_miss"] += 1
                    st["misses"] += 1
                elif self.kv_replication < 1 and holders \
                        and rep.addr not in holders \
                        and self.cross_host_resume < 1.0 \
                        and rng.random() >= self.cross_host_resume:
                    # Landed off the parker's host and the artifact
                    # did not travel: a counted cold re-prefill.
                    st["cross_host_miss"] += 1
                    st["misses"] += 1
                elif ent[1] == rep.weights_version:
                    session_hit = True
                    session_forward = (self.kv_replication >= 1
                                       and rep.addr not in alive)
                    eff_prompt = prompt_len - ent[0]
                    st["hits"] += 1
                    st["resume"] += 1
                    if session_forward:
                        st["forwarded"] += 1
                else:
                    st["version_miss"] += 1
                    st["misses"] += 1
            else:
                st["misses"] += 1
        ttft_s, total_s = rep.model.service_s(eff_prompt, new_tokens, rng)
        if session_forward:
            # The artifact streams over from a surviving copy host
            # before the tail prefill: a wire cost, not a recompute.
            fwd = self.kv_forward_ms / 1000.0
            ttft_s += fwd
            total_s += fwd
        resumed = msg.get("resumed_tokens")
        if op == "prefill":
            total_s = ttft_s            # prefill tier: no decode tail
        elif isinstance(resumed, int) and resumed > 0:
            # A drain-migration artifact re-imported mid-stream: the
            # survivor decodes only the REMAINING tokens — no prefill
            # re-run (that is the whole point of carrying the bytes).
            remaining = max(1, new_tokens - resumed)
            total_s = rep.model.decode_ms_per_token * remaining / 1000.0
            ttft_s = min(ttft_s, total_s)
        elif rep.role == DECODE:
            total_s = max(0.0, total_s - ttft_s)    # imported prefill
            ttft_s = 0.0
        if rep.slow_factor != 1.0:
            ttft_s *= rep.slow_factor
            total_s *= rep.slow_factor
        reply: Any
        if rep.error_rate and rng.random() < rep.error_rate:
            start, finish = rep.occupy(now, min(total_s, 0.001))
            reply = {"op": "error", "kind": "internal",
                     "error": "scripted transient failure"}
        else:
            start, finish = rep.occupy(now, total_s)
            dl = msg.get("deadline_ms")
            if isinstance(dl, (int, float)) and not isinstance(dl, bool) \
                    and dl > 0 and finish > now + dl / 1000.0:
                # The in-batcher deadline cancel: explicit error at the
                # deadline, THIS row's slot freed early.
                cut = now + dl / 1000.0
                rep.release_to(finish, cut)
                finish = cut
                reply = {"op": "error", "kind": "deadline_exceeded",
                         "error": "deadline expired in the batcher"}
            elif op == "prefill":
                reply = wire.RawFrame(
                    {"op": "prefill", "id": 0,
                     "weights_version": rep.weights_version,
                     "gen": rep.gen,
                     "prefill_ms": round((finish - now) * 1000.0, 3)},
                    b"")
            else:
                reply = {"op": "completion", "tokens": _EMPTY_TOKENS,
                         "n_tokens": new_tokens,
                         "ttft_ms": round(
                             (start + ttft_s - now) * 1000.0, 3),
                         "total_ms": round((finish - now) * 1000.0, 3)}
                if sid is not None and op == "generate":
                    # Park the finished conversation's coverage (the
                    # last emitted token is the next turn's tail
                    # input, like the real artifact's history).
                    holders: Any = rep.addr
                    if self.kv_replication >= 1:
                        holders = self._place(sid, rep.addr)
                        load = self._tier_load
                        prev = self.session_tier.get(sid)
                        if prev is not None and len(prev) > 2 \
                                and not isinstance(prev[2], str):
                            for a in prev[2]:   # re-park replaces copies
                                if load.get(a, 0) > 0:
                                    load[a] -= 1
                        for a in holders:
                            load[a] = load.get(a, 0) + 1
                    self.session_tier[sid] = (
                        prompt_len + new_tokens - 1,
                        rep.weights_version, holders)
                    st = self.session_stats
                    st["park"] += 1
                    st["ttft_hit_ms" if session_hit
                       else "ttft_cold_ms"] += reply["ttft_ms"]
        rep.served += 1
        t_wake = finish
        exc: Optional[BaseException] = None
        if timeout is not None and finish > now + timeout:
            t_wake = now + timeout
            exc = CallTimeout(f"no reply from {link.addr} within "
                              f"{timeout}s (sim)")
        if not rep._pending and eng.fast_forward(t_wake):
            # No intervening event: resolve in-line, no thread handoff.
            if exc is not None:
                raise exc
            return reply
        me = eng._current
        rec = [False, me, msg]
        rep._pending.append(rec)

        def wake() -> None:
            if not rec[0]:
                rec[0] = True
                eng._resume(me, reply, exc)

        eng.at(t_wake, wake)
        link._outstanding += 1
        try:
            return eng.park()
        finally:
            link._outstanding -= 1
            rec[0] = True
            try:
                rep._pending.remove(rec)
            except ValueError:
                pass


# -- configuration -----------------------------------------------------------


@dataclasses.dataclass
class SimConfig:
    """One simulation's fleet + policy configuration.  Every policy
    constant the control plane guesses at is addressable here by sweep
    path (``breaker.*``, ``autoscaler.*``, ``admission.*``,
    ``budget.*``, ``router.*``, ``model.*``, or a top-level field) —
    see :func:`apply_override`."""

    seed: int = 0
    replicas: int = 3
    prefill_replicas: int = 0
    decode_replicas: int = 0
    capacity: int = 4
    kv_pages: int = 64
    # Model catalog (the multi-model scenario): (model_id, boot
    # replicas) entries, a warm pool of undedicated replicas, the
    # fleet-wide budget the trader reallocates within (None = boot
    # footprint), and the trader's knobs — all sweepable
    # (``catalog.warm_pool``, ``catalog.budget``, ``trader.*``).
    models: Tuple[Tuple[str, int], ...] = ()
    warm_pool: int = 0
    model_budget: Optional[int] = None
    trader: "TraderConfig" = dataclasses.field(
        default_factory=lambda: TraderConfig())
    # N stateless gateway "fibers" over the ONE registry/router view —
    # the sim analog of `tfserve --gateways N` (each front door gets
    # its own AdmissionController + dispatch-worker fibers; arrivals
    # round-robin across live fronts like clients spreading
    # connections).  1 = the classic single-gateway topology, exactly.
    gateways: int = 1
    # Gang replicas (the ``gang`` scenario; sweep ``gang_size=2,4,8``):
    # each unified replica stands for an N-member pod-slice gang —
    # per-token compute divides by size × gang_efficiency, a member's
    # death is the gang's death, and the fleet re-forms it whole after
    # gang_reform_s (launch + rendezvous + re-warm).
    gang_size: int = 1
    gang_efficiency: float = 0.85
    gang_reform_s: float = 2.0
    # Cross-host resume probability for parked sessions (the sessions
    # scenario's gang-parked-shard knob; sweep ``cross_host_resume=
    # 1.0,0.5,0.0``).  1.0 = the host-shared tier, exactly.
    cross_host_resume: float = 1.0
    # Cross-host KV fabric placement policy (the sessions scenario;
    # sweep ``kv_replication=1,2,3`` and ``kv_forward_ms`` to tune the
    # replication factor and forwarding constant on the virtual
    # clock): 0 = the host-shared disk tier above, exactly.  K >= 1
    # switches to per-host tiers with K-way rendezvous-placed parking
    # — a kill loses only sessions whose every copy host died.
    kv_replication: int = 0
    kv_forward_ms: float = 2.0
    # Copy-placement policy when K >= 1 (sweep ``kv_placement=
    # rendezvous,loaded``): "loaded" stable-sorts the rendezvous
    # ranking by tier occupancy before truncating to K-1 copies —
    # KVFabric's ``placement=loaded`` knob priced on the virtual clock.
    kv_placement: str = "rendezvous"
    workers: int = 8
    max_queue: int = DEFAULT_MAX_QUEUE
    rate_limit: Optional[float] = None
    # (name, weight, rank) entries, optionally (name, weight, rank,
    # batch): a truthy 4th element marks the deadline-less BATCH class
    # (dispatches only when every non-batch queue is empty — the
    # offline lane, docs/SERVING.md).
    classes: Tuple[Tuple[str, float, int], ...] = (
        ("interactive", 8.0, 1), ("background", 1.0, 0))
    # The offline lane (`tfserve --batch-lane`): True appends a
    # deadline-less 'batch' class below every listed class.
    batch_lane: bool = False
    # Interactive-vs-batch budget split (sweep ``batch_slot_frac=
    # 0.25,0.5,0.75,1.0``): the fraction of the fleet's aggregate
    # decode slots batch-lane work may occupy at once — the sim analog
    # of batch rows taking only idle slots and leftover tick budget,
    # yielding the rest to interactive arrivals.  1.0 = no reserve.
    batch_slot_frac: float = 0.5
    model: ReplicaModel = dataclasses.field(default_factory=ReplicaModel)
    breaker: BreakerConfig = dataclasses.field(
        default_factory=BreakerConfig)
    breakers: bool = True
    autoscaler: AutoscalerConfig = dataclasses.field(
        default_factory=AutoscalerConfig)
    autoscale: bool = False
    min_replicas: int = 1
    max_replicas: int = 64
    budget_max_tokens: float = 10.0
    budget_token_ratio: float = 0.1
    max_retries: int = 2
    backoff_s: float = 0.05
    request_timeout: float = 60.0
    hb_interval: float = 0.5
    # Heartbeat sharding (the diurnal 10k-replica scenario): 0 keeps
    # one timer event per replica per beat — the classic behavior,
    # exactly.  N > 0 batches replicas into N self-rescheduling shard
    # beats, collapsing the event heap's dominant term at 10k replicas
    # (10k events/sim-second -> N) without changing what the registry
    # observes.  Opt-in because it quantizes beat phases per shard.
    hb_shards: int = 0
    suspect_after: float = 1.5
    dead_after: float = 3.0
    evict_after: float = 10.0
    sweep_interval: float = 0.2
    warmup_s: float = 1.0
    weights_version: str = "v1"


_OVERRIDE_ROOTS = {
    "breaker": lambda cfg: cfg.breaker,
    "autoscaler": lambda cfg: cfg.autoscaler,
    "model": lambda cfg: cfg.model,
    "trader": lambda cfg: cfg.trader,
}
_OVERRIDE_ALIASES = {
    "admission.max_queue": "max_queue",
    "admission.rate": "rate_limit",
    "budget.max_tokens": "budget_max_tokens",
    "budget.token_ratio": "budget_token_ratio",
    "router.max_retries": "max_retries",
    "router.backoff_s": "backoff_s",
    "router.request_timeout": "request_timeout",
    "catalog.warm_pool": "warm_pool",
    "catalog.budget": "model_budget",
}


def _coerce(old: Any, value: str) -> Any:
    if isinstance(old, bool):
        return value.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(old, int) and not isinstance(old, bool):
        return int(float(value))
    if isinstance(old, float) or old is None:
        return float(value)
    return value


def apply_override(cfg: SimConfig, path: str, value) -> None:
    """Set one policy constant by dotted path (``breaker.
    latency_factor``, ``autoscaler.queue_wait_hi_ms``,
    ``admission.max_queue``, ``budget.token_ratio``,
    ``router.max_retries``, ``model.decode_ms_per_token``, or a
    top-level ``SimConfig`` field like ``replicas``).  String values
    are coerced to the field's current type."""
    alias = _OVERRIDE_ALIASES.get(path)
    if alias is not None:
        target, field = cfg, alias
    elif "." in path:
        root, field = path.split(".", 1)
        getter = _OVERRIDE_ROOTS.get(root)
        if getter is None or "." in field:
            raise ValueError(f"unknown sweep path {path!r}")
        target = getter(cfg)
    else:
        target, field = cfg, path
    if not hasattr(target, field):
        raise ValueError(f"unknown sweep path {path!r}")
    old = getattr(target, field)
    setattr(target, field,
            _coerce(old, value) if isinstance(value, str) else value)


def swept(overrides, field: str) -> bool:
    """True when any override path targets ``field``, ALIASES RESOLVED
    (``admission.max_queue`` targets ``max_queue``) — scenarios use
    this to lay in scale defaults without clobbering a sweep's
    explicit choice of the same constant."""
    for p, _ in (overrides or ()):
        if p == field or _OVERRIDE_ALIASES.get(p) == field:
            return True
    return False


def parse_sweep(spec: str) -> Tuple[str, List[str]]:
    """``"breaker.latency_factor=2,4,8"`` -> ``("breaker.
    latency_factor", ["2", "4", "8"])``."""
    if "=" not in spec:
        raise ValueError(f"sweep spec needs PATH=V1,V2,...: {spec!r}")
    path, _, values = spec.partition("=")
    vals = [v for v in values.split(",") if v != ""]
    if not path or not vals:
        raise ValueError(f"sweep spec needs PATH=V1,V2,...: {spec!r}")
    return path.strip(), vals


# -- the simulation harness --------------------------------------------------


class _SimFront:
    """One simulated gateway front door: its own WFQ admission
    controller + idle dispatch-worker deque + alive flag.  Stateless
    beyond its queues — any front serves any request, which is what
    makes killing one a pure re-queue event."""

    __slots__ = ("idx", "admission", "idle", "dead")

    def __init__(self, idx: int, admission: AdmissionController):
        self.idx = idx
        self.admission = admission
        self.idle: deque = deque()
        self.dead = False


class FleetSim:
    """One simulated fleet: the real control plane wired to virtual
    replicas.  Also implements the dynamic-fleet surface
    (``targets`` / ``bounds`` / ``launch_replica`` / ``kill_replica``
    / ``tier_actual`` / ``scale_lock`` / ``request_migration``) so the
    REAL :class:`FleetAutoscaler` actuates simulated capacity."""

    def __init__(self, cfg: SimConfig):
        self.cfg = cfg
        self.log = get_logger("tfmesos_tpu.fleet.sim")
        eng = self.engine = SimEngine(cfg.seed)
        self.metrics = FleetMetrics()
        self.registry = ReplicaRegistry(
            clock=eng.clock, suspect_after=cfg.suspect_after,
            dead_after=cfg.dead_after, evict_after=cfg.evict_after,
            sweep_interval=cfg.sweep_interval, metrics=self.metrics)
        self.transport = SimTransport(eng)
        specs = [PriorityClass(c[0], weight=c[1], rank=c[2],
                               batch=bool(c[3]) if len(c) > 3 else False)
                 for c in cfg.classes]
        if cfg.batch_lane and not any(s.batch for s in specs):
            # The offline lane (mirrors FleetServer's --batch-lane):
            # a deadline-less batch class ranked below everything.
            floor = min(s.rank for s in specs) if specs else 0
            specs.append(PriorityClass("batch", weight=1.0,
                                       rank=floor - 1, batch=True))
        self._batch_cls = {s.name for s in specs if s.batch}
        self._batch_busy = 0
        # Front doors: N stateless gateways over the one registry/
        # router view (`tfserve --gateways N`).  Each gets its own
        # AdmissionController (its WFQ queues) + idle-worker deque;
        # specs are immutable and shared (WFQ state lives in the
        # controller).  ``self.admission`` stays the FIRST front's
        # controller — the single-gateway back-compat alias every
        # existing scenario and test drives.
        self.fronts: List[_SimFront] = []
        for i in range(max(1, int(cfg.gateways))):
            adm = AdmissionController(
                max_queue=cfg.max_queue, rate=cfg.rate_limit,
                classes=specs, clock=eng.clock)
            adm.on_expired = self._queue_expired
            self.fronts.append(_SimFront(i, adm))
        self.admission = self.fronts[0].admission
        self._rr = 0                # round-robin arrival spread
        self.gateway_failovers = 0  # items replayed off a killed front
        self.budget = RetryBudget(cfg.budget_max_tokens,
                                  cfg.budget_token_ratio)
        self.router = Router(
            self.registry, self.metrics, max_retries=cfg.max_retries,
            backoff_s=cfg.backoff_s, request_timeout=cfg.request_timeout,
            rng=random.Random(cfg.seed + 1), breakers=cfg.breakers,
            breaker_config=cfg.breaker, retry_budget=self.budget,
            clock=eng.clock, sleep=eng.sleep,
            link_factory=self.transport.link)
        # Dynamic-fleet surface for the real autoscaler / trader.
        self.targets: Dict[str, int] = {}
        self.scale_lock = threading.RLock()
        self.autoscaler: Optional[FleetAutoscaler] = None
        self.replica_budget: Optional[int] = None
        self.trajectory: List[dict] = []
        # Bookkeeping.  ``planned`` is the number of requests the
        # scenario intends to submit — the completion predicate
        # (``drained``) compares against it, never against ``injected``
        # (a closed-loop feeder between iterations would otherwise
        # read as "all done" and end the run early).
        self.planned = 0
        self.injected = 0
        self.finished = 0
        self.completed = 0
        self.shed = 0
        self.deadline_errors = 0
        self.expired_in_queue = 0
        self.conformance_violations = 0
        self.lost: List[BaseException] = []
        self._eps_s = 0.005
        self._next_rid = 0
        self._stopped = False
        # Hot-path histogram handles (one dict lookup per request
        # instead of name formatting + registry locks at 1M-request
        # scale); results() still reads them by name.
        self._h_queue_wait = self.metrics.hist("queue_wait_ms")
        self._h_ttft = self.metrics.hist("ttft_ms")
        self._h_latency = self.metrics.hist("latency_ms")
        self._cls_hist = {
            s.name: (self.metrics.hist(f"queue_wait_ms_{s.name}"),
                     self.metrics.hist(f"latency_ms_{s.name}"),
                     f"latency_ms_{s.name}")
            for s in specs}
        self._prompts: Dict[int, tuple] = {}
        # Heartbeat sharding (cfg.hb_shards): None = one timer event
        # per replica per beat; else N shard lists, each driven by one
        # self-rescheduling event that beats every live member.
        n_sh = max(0, int(cfg.hb_shards))
        self._hb_shards: Optional[List[List[SimReplica]]] = (
            [[] for _ in range(n_sh)] if n_sh else None)
        self._hb_live = [False] * n_sh
        # The liveness sweep is always on; heartbeats are per-replica.
        self._schedule_sweep()

    # -- replica lifecycle -------------------------------------------------

    def add_replica(self, role: str = UNIFIED,
                    capacity: Optional[int] = None,
                    model: Optional[ReplicaModel] = None,
                    weights_version: Optional[str] = None,
                    warm_s: float = 0.0, model_id: str = "",
                    pool: bool = False,
                    gang_size: Optional[int] = None) -> SimReplica:
        self._next_rid += 1
        i = self._next_rid
        size = self.cfg.gang_size if gang_size is None else int(gang_size)
        base = model or self.cfg.model
        if size > 1:
            base = gang_model(base, size, self.cfg.gang_efficiency)
        rep = SimReplica(
            addr=f"sim-{role[:3]}-{i}", role=role,
            capacity=capacity if capacity is not None else self.cfg.capacity,
            model=base,
            weights_version=weights_version or self.cfg.weights_version,
            node=f"sim:{i}", kv_pages=self.cfg.kv_pages,
            warm_until=self.engine.clock.now + warm_s,
            model_id=model_id, pool=pool, gang_size=size)
        self.transport.replicas[rep.addr] = rep
        if self._hb_shards is not None:
            # Sharded beats: register NOW (scenarios wait on the
            # registry seeing the replica), then join a shard whose
            # one event beats every member each interval.
            if not rep.drop_beats:
                self._send_beat(rep)
            idx = i % len(self._hb_shards)
            self._hb_shards[idx].append(rep)
            if not self._hb_live[idx]:
                self._hb_live[idx] = True
                self.engine.after(self.cfg.hb_interval,
                                  lambda: self._shard_beat(idx))
        else:
            self._beat(rep)
        return rep

    def _beat(self, rep: SimReplica) -> None:
        if rep.removed or rep.down or self._stopped:
            return      # a dead replica stops beating; the sweep notices
        if not rep.drop_beats:
            self._send_beat(rep)
        self.engine.after(self.cfg.hb_interval, lambda: self._beat(rep))

    def _shard_beat(self, idx: int) -> None:
        if self._stopped:
            return
        shard = [r for r in self._hb_shards[idx]
                 if not r.removed and not r.down]
        self._hb_shards[idx] = shard
        if not shard:
            self._hb_live[idx] = False
            return      # re-armed when the shard gains a replica
        for rep in shard:
            if not rep.drop_beats:
                self._send_beat(rep)
        # Logical-event accounting: this ONE heap pop carried
        # len(shard) beats that per-replica mode pops individually —
        # credit them so ``sim_events_per_sec`` means the same thing
        # at every ``hb_shards`` setting.
        self.engine.events += len(shard) - 1
        self.engine.after(self.cfg.hb_interval,
                          lambda: self._shard_beat(idx))

    def _send_beat(self, rep: SimReplica) -> None:
        now = self.engine.clock.now
        msg: Dict[str, Any] = {
            "op": "heartbeat", "addr": rep.addr,
            "capacity": rep.capacity,
            "outstanding": rep.outstanding(now), "role": rep.role,
            "node": rep.node,
            "weights_version": rep.weights_version, "gen": rep.gen}
        if rep.model_id:
            msg["model_id"] = rep.model_id
        if rep.pool or rep.model_id:
            # Like the real replica: pool-capable processes always
            # send the flag, so an adoption's False overwrites.
            msg["warm_pool"] = rep.pool
        if rep.gang_size > 1:
            # The leader-only gang beat field the real registry
            # parses into ReplicaInfo.gang_* / gang_summary().
            msg["gang"] = {"id": f"sim/{rep.node}",
                           "size": rep.gang_size,
                           "live": rep.gang_live,
                           "coord": rep.addr}
        if rep.role == DECODE:
            msg["kv_headroom"] = max(
                0, rep.kv_pages - rep.outstanding(now))
        if now < rep.warm_until:
            msg["status"] = WARMING
        self.registry.observe(msg)

    def kill(self, rep: SimReplica) -> None:
        """Hard death (the SIGKILL analog): beats stop, in-flight
        calls fail with :class:`ConnectionLost` now, the registry
        notices through the router's mark_dead or the sweep."""
        rep.down = True
        self.transport.fail_pending(rep)

    def kill_gang_member(self, rep: SimReplica) -> Optional[SimReplica]:
        """SIGKILL one MEMBER of a gang replica: the gang dies whole
        (the leader tears down; pending calls fail now and replay on
        survivors), and the fleet re-forms it — a fresh gang, fresh
        rendezvous, re-warm — after ``cfg.gang_reform_s``.  Returns
        the dying replica (the re-formed one appears asynchronously)."""
        if rep.gang_size <= 1 or rep.down or rep.removed:
            return None
        rep.gang_live = rep.gang_size - 1
        self.kill(rep)
        rep.removed = True
        self.metrics.inc("gang_deaths")
        role, size = rep.role, rep.gang_size
        wv, mid = rep.weights_version, rep.model_id

        def reform() -> None:
            if self._stopped:
                return
            self.add_replica(role=role, gang_size=size,
                             warm_s=self.cfg.warmup_s,
                             weights_version=wv, model_id=mid)
            self.metrics.inc("gang_reforms")

        self.engine.after(self.cfg.gang_reform_s, reform)
        return rep

    def _schedule_sweep(self) -> None:
        if self._stopped:
            return
        self.registry.sweep()
        self.engine.after(self.cfg.sweep_interval, self._schedule_sweep)

    # -- the dynamic-fleet surface (real FleetAutoscaler actuates it) ------

    def set_target(self, role: str, n: int) -> None:
        self.targets[role] = int(n)
        self.registry.set_target(role, int(n))

    def bounds(self, role: str) -> Tuple[int, int]:
        return (self.cfg.min_replicas, self.cfg.max_replicas)

    def launch_replica(self, key: str,
                       weights_version: Optional[str] = None) -> str:
        model, role = split_key(key)
        rep = self.add_replica(role=role, warm_s=self.cfg.warmup_s,
                               weights_version=weights_version,
                               model_id=(model if model not in
                                         (None, POOL) else ""),
                               pool=model == POOL)
        return rep.node

    def kill_replica(self, node: str) -> bool:
        for rep in self.transport.replicas.values():
            if rep.node == node and not rep.removed:
                self.kill(rep)
                rep.removed = True
                return True
        return False

    def tier_actual(self, key: str) -> int:
        model, role = split_key(key)
        out = 0
        for r in self.transport.replicas.values():
            if r.down or r.removed or (r.role or UNIFIED) != role:
                continue
            if model == POOL:
                out += 1 if r.pool else 0
            elif model is not None:
                out += 1 if r.model_id == model else 0
            else:
                out += 1
        return out

    def tier_members(self, key: str):
        from tfmesos_tpu.fleet.catalog import filter_members
        model, role = split_key(key)
        return filter_members(self.registry.members(role), key)

    def adopt_replica(self, addr: str, model_id: str) -> bool:
        """The sim's warm-pool adoption: flip the replica's model
        identity (the real path installs weights — here it is
        instantaneous) and inject one immediate beat so routing views
        follow without waiting a heartbeat interval."""
        rep = self.transport.replicas.get(addr)
        if rep is None or rep.down or rep.removed or not rep.pool:
            return False
        rep.model_id = model_id
        rep.pool = False
        self.registry.observe({
            "op": "heartbeat", "addr": rep.addr,
            "capacity": rep.capacity,
            "outstanding": rep.outstanding(self.engine.clock.now),
            "role": rep.role, "node": rep.node,
            "weights_version": rep.weights_version, "gen": rep.gen,
            "model_id": model_id, "warm_pool": False})
        self.metrics.inc("sim_adoptions")
        return True

    def request_migration(self, addr: str) -> None:
        rep = self.transport.replicas.get(addr)
        if rep is not None:
            rep.migrating = True
            self.transport.suspend_pending(rep)

    def enable_autoscaler(self) -> FleetAutoscaler:
        """Attach the REAL autoscaler (its default registry+metrics
        signal source) and schedule its ticks on the virtual clock."""
        self.autoscaler = FleetAutoscaler(self, self.cfg.autoscaler,
                                          clock=self.engine.clock)
        self._auto_tick()
        return self.autoscaler

    def enable_trader(self, catalog: ModelCatalog) -> ModelTrader:
        """Attach the REAL model trader (the per-(model, tier)
        generalization of the autoscaler) on the virtual clock, wire
        the router's cold-start demand hook to it, and schedule its
        ticks — the multi-model scenario's control plane."""
        self.replica_budget = self.cfg.model_budget
        trader = ModelTrader(self, catalog, self.cfg.autoscaler,
                             trader_config=self.cfg.trader,
                             clock=self.engine.clock)
        self.autoscaler = trader
        self.router.on_model_demand = trader.demand
        self._auto_tick()
        return trader

    def _auto_tick(self) -> None:
        if self._stopped or self.autoscaler is None:
            return
        self.autoscaler.step()
        desc = self.autoscaler.describe()
        self.trajectory.append(
            {"t": round(self.engine.clock.now, 3),
             **{role: {"target": d["target"], "actual": d["actual"],
                       "alive": d["alive"]}
                for role, d in desc.items()}})
        if len(self.trajectory) > 10000:
            del self.trajectory[:5000]
        self.engine.after(self.cfg.autoscaler.interval, self._auto_tick)

    # -- traffic -----------------------------------------------------------

    def _prompt(self, n: int) -> tuple:
        p = self._prompts.get(n)
        if p is None:
            p = self._prompts[n] = tuple(range(n))
        return p

    def _build(self, req: Request) -> tuple:
        """The gateway-receipt analog: resolve the class, stamp the
        absolute deadline, build the forward dict."""
        spec = self.admission.resolve(req.cls)
        now = self.engine.clock.now
        deadline = None
        msg: Dict[str, Any] = {
            "op": "generate", "prompt": self._prompt(req.prompt_len),
            "max_new_tokens": req.new_tokens, "stop_token": None,
            "priority": spec.rank}
        if getattr(spec, "batch", False):
            # Mirrors the gateway: the router prefers replicas with
            # free slots for batch-lane work.
            msg["_background"] = True
        if getattr(req, "session", None):
            msg["session"] = req.session
        if getattr(req, "model", None):
            msg["_model"] = req.model
        if req.deadline_ms is not None and req.deadline_ms > 0:
            deadline = now + req.deadline_ms / 1000.0
            msg["deadline"] = deadline
        return msg, spec, now, deadline

    def _pick_front(self, front) -> Optional["_SimFront"]:
        """The front door this arrival dials: an explicit index, or
        round-robin over the LIVE fronts (clients spreading
        connections); None when every front is dead."""
        if front is not None:
            f = self.fronts[front % len(self.fronts)]
            return None if f.dead else f
        n = len(self.fronts)
        for _ in range(n):
            f = self.fronts[self._rr % n]
            self._rr += 1
            if not f.dead:
                return f
        return None

    def submit(self, req: Request, sink: Optional[list] = None,
               front=None) -> bool:
        """Admit one request (shed bookkeeping mirrors the gateway);
        truthy (the front served) when admitted.  ``sink``, when
        given, receives ``(reply, end_time)`` at completion — how a
        caller observes its OWN request's outcome even when a
        different fiber dispatches it.  ``front`` pins a specific
        gateway; default spreads round-robin over live fronts."""
        f = self._pick_front(front)
        msg, spec, now, deadline = self._build(req)
        self.injected += 1
        m = self.metrics
        m.inc("received")
        if f is None:
            # Every front door is dead: the client's dial fails — an
            # explicit connection error, never a hang.
            m.inc("failed")
            self.shed += 1
            self.finished += 1
            return False
        item = (msg, spec.name, now, deadline, sink)
        try:
            f.admission.admit(item, cls=spec.name, deadline=deadline)
        except DeadlineExceeded:
            m.inc("shed_deadline")
            self.shed += 1
            self.finished += 1
            return False
        except RateLimited:
            m.inc("shed_rate_limited")
            self.shed += 1
            self.finished += 1
            return False
        except Overloaded:
            m.inc("shed_queue")
            m.inc(f"shed_queue_{spec.name}")
            self.shed += 1
            self.finished += 1
            return False
        m.inc("admitted")
        return f

    def _inject(self, req: Request) -> None:
        """Engine-context arrival: admit, then hand work to an idle
        dispatch worker of the front that took it."""
        f = self.submit(req)
        if f and f.idle:
            self.engine._resume(f.idle.popleft())

    def _queue_expired(self, item: tuple) -> None:
        """A queued request's deadline passed before dispatch — the
        explicit-answer path (mirrors Gateway._queue_expired)."""
        _, cls, _, _, sink = item
        self.metrics.inc("shed_deadline")
        self.metrics.inc("failed")
        self.expired_in_queue += 1
        self.finished += 1
        if sink is not None:
            sink.append(({"op": "error", "kind": "deadline_exceeded"},
                         self.engine.clock.now))

    def _batch_cap(self) -> int:
        """Concurrent batch-lane dispatches the budget split allows:
        ``batch_slot_frac`` of the live fleet's aggregate slots — the
        sim analog of batch rows taking only idle decode slots and
        leftover tick budget (docs/SERVING.md "Offline lane")."""
        total = sum(r.capacity for r in self.transport.replicas.values()
                    if not (r.down or r.removed))
        return max(1, int(self.cfg.batch_slot_frac * total))

    def _requeue_batch(self, item: tuple) -> None:
        """Re-admit a budget-deferred batch item (engine context); a
        front at its bound sheds it explicitly, never silently."""
        _, cls, _, deadline, sink = item
        f = self._pick_front(None)
        if f is None:
            self.metrics.inc("failed")
            self.shed += 1
            self.finished += 1
            return
        try:
            f.admission.admit(item, cls=cls, deadline=deadline)
        except (Overloaded, DeadlineExceeded):
            self.metrics.inc("shed_queue")
            self.shed += 1
            self.finished += 1
            if sink is not None:
                sink.append(({"op": "error", "kind": "overloaded"},
                             self.engine.clock.now))
            return
        if f.idle:
            self.engine._resume(f.idle.popleft())

    def dispatch(self, item: tuple) -> Any:
        """Fiber-context: one request through the real router, with
        the gateway worker's metric bookkeeping."""
        msg, cls, t_enq, deadline, sink = item
        eng = self.engine
        m = self.metrics
        is_batch = cls in self._batch_cls
        if is_batch and self._batch_busy >= self._batch_cap():
            # The lane is at its slot split: requeue shortly and free
            # this worker for interactive items NOW — a parked batch
            # item must never hold a dispatcher an interactive
            # arrival needs (the preemption analog at the front).
            m.inc("batch_deferrals")
            eng.after(0.01, lambda: self._requeue_batch(item))
            return None
        cls_h = self._cls_hist.get(cls)
        wait_ms = (eng.clock.now - t_enq) * 1000.0
        self._h_queue_wait.observe(wait_ms)
        if cls_h is not None:
            cls_h[0].observe(wait_ms)
        mlabel = msg.get("_model")
        if mlabel:
            # The per-model queue-wait histogram — the trader's
            # relative-pressure signal, same as the real gateway's.
            m.hist(f"queue_wait_ms_model_{mlabel}").observe(wait_ms)
        if is_batch:
            self._batch_busy += 1
        try:
            reply = self.router.route(msg)
        except Exception as e:  # noqa: BLE001 - every loss recorded
            m.inc("failed")
            self.lost.append(e)
            self.finished += 1
            if sink is not None:
                sink.append((None, eng.clock.now))
            return None
        finally:
            if is_batch:
                self._batch_busy -= 1
        end = eng.clock.now
        if isinstance(reply, dict) and reply.get("op") == "completion":
            m.inc("completed")
            m.inc("tokens_out", int(reply.get("n_tokens") or 0))
            lat_ms = (end - t_enq) * 1000.0
            self._h_ttft.observe(reply.get("ttft_ms") or 0.0)
            self._h_latency.observe(lat_ms)
            if cls_h is not None:
                cls_h[1].observe(lat_ms)
            self.completed += 1
            if deadline is not None and end > deadline + self._eps_s:
                self.conformance_violations += 1
        else:
            m.inc("failed")
            kind = reply.get("kind") if isinstance(reply, dict) else None
            if kind == "deadline_exceeded":
                m.inc("deadline_exceeded")
                self.deadline_errors += 1
                if deadline is not None \
                        and end > deadline + self._eps_s:
                    self.conformance_violations += 1
            else:
                self.lost.append(RuntimeError(f"error reply: {reply!r}"))
        self.finished += 1
        if sink is not None:
            sink.append((reply, end))
        return reply

    def start_workers(self, n: Optional[int] = None) -> None:
        """The dispatch pool (the gateway's worker-thread analog):
        PER-FRONT fibers that drain that front's WFQ queue and park
        when it empties."""
        per = n if n is not None else self.cfg.workers
        for f in self.fronts:
            for i in range(per):
                self.engine.spawn(
                    lambda f=f: self._worker_body(f),
                    name=f"sim-gw{f.idx}-worker-{i}"
                    if len(self.fronts) > 1 else f"sim-worker-{i}")

    def _worker_body(self, front: Optional["_SimFront"] = None) -> None:
        front = front or self.fronts[0]
        eng = self.engine
        while True:
            if front.dead:
                eng.park()          # a killed gateway's pool is gone
                continue
            item = front.admission.get(timeout=0)
            if item is None:
                front.idle.append(eng._current)
                eng.park()
                continue
            self.dispatch(item)

    def kill_gateway(self, idx: int) -> int:
        """Hard-kill one front door mid-traffic (the bench's gateway
        SIGKILL analog): its dispatch pool stops, and every item still
        QUEUED there is re-admitted on a surviving front — the
        client-failover replay (idempotent requests, nothing was
        delivered).  Returns how many items failed over.  Re-admission
        sheds (a survivor at its bound) surface as explicit
        ``overloaded`` answers, never silent losses."""
        f = self.fronts[idx % len(self.fronts)]
        if f.dead:
            return 0
        f.dead = True
        moved = 0
        while True:
            item = f.admission.get(timeout=0)
            if item is None:
                break
            msg, cls, t_enq, deadline, sink = item
            target = self._pick_front(None)
            if target is None:
                self.metrics.inc("failed")
                self.shed += 1
                self.finished += 1
                continue
            try:
                target.admission.admit(item, cls=cls, deadline=deadline)
            except (Overloaded, DeadlineExceeded):
                self.metrics.inc("shed_queue")
                self.shed += 1
                self.finished += 1
                continue
            moved += 1
            if target.idle:
                self.engine._resume(target.idle.popleft())
        self.gateway_failovers += moved
        self.metrics.inc("gateway_failovers", moved)
        self.log.info("gateway %d killed; %d queued item(s) failed "
                      "over", idx, moved)
        return moved

    def feed(self, workload) -> None:
        """Schedule an open-arrival workload (lazily: one pending
        arrival event at a time, so a million-request stream never
        materializes in memory)."""
        n = getattr(workload, "n_requests", None)
        if n is None:
            try:
                n = len(workload)
            except TypeError:
                raise ValueError(
                    "open workloads need a known size (n_requests or "
                    "__len__) for the completion predicate") from None
        self.planned += int(n)
        it = iter(workload)

        def chain() -> None:
            req = next(it, None)
            if req is None:
                return
            self.engine.at(req.at, lambda: (self._inject(req), chain()))

        first = next(it, None)
        if first is not None:
            self.engine.at(first.at,
                           lambda: (self._inject(first), chain()))
        else:
            self.planned -= int(n)

    def spawn_feeder(self, reqs, record: Optional[list] = None,
                     stop: Optional[Callable[[], bool]] = None) -> None:
        """Closed-loop feeder fiber over a request LIST: submit one,
        then serve one WFQ-dispatched item (its own or a peer's — net
        flow conserved, WFQ order preserved), like the soak bench's
        client threads."""
        reqs = list(reqs)
        self.planned += len(reqs)

        def body() -> None:
            done = 0
            for req in reqs:
                if stop is not None and stop():
                    break
                t0 = self.engine.clock.now
                done += 1
                # Closed-loop feeders serve what they submit: pin to
                # front 0 so net flow stays conserved per queue.
                if not self.submit(req, front=0):
                    continue
                item = self.admission.get(timeout=0)
                if item is None:
                    continue        # another fiber raced it away
                self.dispatch(item)
                if record is not None:
                    record.append(
                        (self.engine.clock.now - t0) * 1000.0)
            self.planned -= len(reqs) - done

        self.engine.spawn(body, name="sim-feeder")

    # -- lifecycle / results -----------------------------------------------

    def drained(self) -> bool:
        """Every PLANNED request answered (completion, shed, or
        explicit error) — the scenario completion predicate."""
        return self.planned > 0 and self.finished >= self.planned

    def stop(self) -> None:
        self._stopped = True
        self.engine.stop_fibers()

    def results(self, wall_s: float) -> Dict[str, Any]:
        m = self.metrics
        completed = max(1, m.get("completed"))
        out: Dict[str, Any] = {
            "sim_seconds": round(self.engine.clock.now, 3),
            "events": self.engine.events,
            "sim_events_per_sec": round(
                self.engine.events / max(1e-9, wall_s), 1),
            "sim_replicas_per_wallclock_sec": round(
                len(self.transport.replicas) * self.engine.clock.now
                / max(1e-9, wall_s), 1),
            "wall_s": round(wall_s, 3),
            "requests": self.injected,
            "completed": m.get("completed"),
            "failed": m.get("failed"),
            "lost": len(self.lost),
            "retries": m.get("retries"),
            "retry_amplification": round(
                (m.get("completed") + m.get("retries")) / completed, 4),
            "deadline_errors": self.deadline_errors,
            "conformance_violations": self.conformance_violations,
            "shed": self.admission.shed_counts(),
            "breakers": self.router.breaker_summary(),
            "retry_budget": self.router.retry_budget_level(),
            "classes": {},
        }
        # Fleet utilization: slot-seconds served over slot-seconds
        # offered (static-fleet gauge; a replica's whole lifetime
        # counts as offered — the offline lane's win is THIS number
        # rising while interactive latency holds).
        span = self.engine.clock.now
        offered = sum(r.capacity for r in self.transport.replicas.values()
                      if not r.removed) * span
        if offered > 0:
            busy = sum(r.busy_s for r in self.transport.replicas.values())
            out["utilization"] = round(min(1.0, busy / offered), 4)
        if self._batch_cls:
            out["batch_deferrals"] = m.get("batch_deferrals")
        for name, (_, _, lat_name) in self._cls_hist.items():
            cur = m.hist_cumulative(lat_name)
            if cur is None:
                continue
            out["classes"][name] = {
                "count": cur[2],
                "p50_ms": m.percentile(lat_name, 0.50),
                "p90_ms": m.percentile(lat_name, 0.90),
                "p99_ms": m.percentile(lat_name, 0.99),
            }
        qw = m.hist_cumulative("queue_wait_ms")
        if qw is not None:
            out["queue_wait_p99_ms"] = m.percentile("queue_wait_ms", 0.99)
        if self.trajectory:
            out["autoscaler_trajectory"] = list(self.trajectory)
        return out


# -- scenarios ---------------------------------------------------------------


def _new_cfg(base: Optional[SimConfig], overrides) -> SimConfig:
    cfg = dataclasses.replace(base) if base is not None else SimConfig()
    # dataclasses.replace shares the nested mutable configs: deep-copy
    # them so a sweep's override never leaks into its siblings.
    cfg.model = dataclasses.replace(cfg.model)
    cfg.breaker = dataclasses.replace(cfg.breaker)
    cfg.autoscaler = dataclasses.replace(cfg.autoscaler)
    cfg.trader = dataclasses.replace(cfg.trader)
    for path, value in overrides or ():
        apply_override(cfg, path, value)
    return cfg


def scenario_steady(overrides=(), n_requests: int = 4000,
                    replicas: Optional[int] = None,
                    rate: Optional[float] = None,
                    seed: Optional[int] = None,
                    workload=None, model_fit: Optional[dict] = None,
                    cfg: Optional[SimConfig] = None) -> Dict[str, Any]:
    """Steady-state open arrivals against a fixed unified tier: the
    capacity-planning baseline (per-class latency percentiles and shed
    rates at a given replica count and arrival rate)."""
    cfg = _new_cfg(cfg, overrides)
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    # The dispatch pool must not be the bottleneck the scenario
    # measures — size it to cover the fleet's concurrency.
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.replicas * cfg.capacity))
    sim = FleetSim(cfg)
    for _ in range(cfg.replicas):
        sim.add_replica(UNIFIED)
    for _ in range(cfg.prefill_replicas):
        sim.add_replica(PREFILL)
    for _ in range(cfg.decode_replicas):
        sim.add_replica(DECODE)
    if workload is None:
        _, per_req_s = cfg.model.service_s(64, 16, random.Random(0))
        fleet_rate = cfg.replicas * cfg.capacity / max(1e-9, per_req_s)
        workload = SyntheticWorkload(
            n_requests=n_requests, seed=cfg.seed,
            rate=rate if rate is not None else 0.7 * fleet_rate,
            class_mix={"interactive": 1.0, "background": 2.0},
            prompt_len=64, new_tokens=16)
    sim.feed(workload)
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    sim.stop()
    return out


def scenario_surge(overrides=(), n_requests: int = 6000,
                   replicas: Optional[int] = None,
                   seed: Optional[int] = None,
                   workload=None, model_fit: Optional[dict] = None,
                   cfg: Optional[SimConfig] = None) -> Dict[str, Any]:
    """A 4x arrival-rate step against an autoscaled tier: reports the
    autoscaler trajectory (tick-by-tick target/actual/alive) — the
    hysteresis-tuning scenario (``--sweep autoscaler.queue_wait_hi_ms=
    200,500,2000``)."""
    cfg = _new_cfg(cfg, overrides)
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    cfg.autoscale = True
    # Workers cover the scaled-out fleet so added replicas actually
    # relieve the queue (the pool is the gateway-dispatcher analog).
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.max_replicas * cfg.capacity))
    sim = FleetSim(cfg)
    for _ in range(cfg.replicas):
        sim.add_replica(UNIFIED)
    sim.set_target(UNIFIED, cfg.replicas)
    sim.enable_autoscaler()
    _, per_req_s = cfg.model.service_s(64, 16, random.Random(0))
    base_rate = 0.5 * cfg.replicas * cfg.capacity / max(1e-9, per_req_s)
    if workload is None:
        calm = SyntheticWorkload(
            n_requests=n_requests // 3, seed=cfg.seed, rate=base_rate,
            class_mix={"interactive": 1.0, "background": 1.0})
        surge_start = max(r.at for r in calm) if n_requests >= 3 else 0.0
        surge = SyntheticWorkload(
            n_requests=n_requests - n_requests // 3, seed=cfg.seed + 1,
            rate=4.0 * base_rate,
            class_mix={"interactive": 1.0, "background": 1.0},
            start_at=surge_start)
        sim.feed(calm)
        sim.feed(surge)
    else:
        sim.feed(workload)
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out["autoscaled_to"] = sim.tier_actual(UNIFIED)
    sim.stop()
    return out


def scenario_soak_replay(overrides=(), n_per_feeder: int = 120,
                         seed: Optional[int] = None,
                         replicas: Optional[int] = None,
                         workload=None, model_fit: Optional[dict] = None,
                         cfg: Optional[SimConfig] = None
                         ) -> Dict[str, Any]:
    """THE FIDELITY GATE: the seeded ``bench_fleet_soak`` chaos
    timeline replayed through the real control plane on the virtual
    clock — a gray-slow replica under two-class deadline-carrying
    traffic, short-deadline probes, a hard kill + real-autoscaler
    self-heal, a one-shot link sever, and a blue-green rollout.  The
    qualitative contract (asserted in tier-1, tests/test_sim.py):

    * the slow replica is breaker-isolated (``latency_outlier``) while
      the registry still reports it ALIVE — the gray failure;
    * zero lost requests across kill, sever, and rollout;
    * retry amplification <= 1.5;
    * deadline probes answer ``deadline_exceeded`` at ~their deadline.
    """
    cfg = _new_cfg(cfg, overrides)
    if seed is not None:
        cfg.seed = int(seed)
    cfg.replicas = int(replicas) if replicas is not None else 3
    cfg.capacity = 2
    cfg.workers = 0                     # closed-loop feeders dispatch
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    # The soak's shape at sim scale: ~10ms services, a 25x-gray victim
    # (the bench's 0.25s slow_task against CPU-replica ~10ms decodes),
    # liveness clocks as shipped so the kill is detected by heartbeat
    # loss exactly like the bench.
    cfg.model = dataclasses.replace(cfg.model, jitter=cfg.model.jitter
                                    or 0.05)
    sim = FleetSim(cfg)
    eng = sim.engine
    reps = [sim.add_replica(UNIFIED) for _ in range(cfg.replicas)]
    victim = min(reps, key=lambda r: r.addr)
    victim.slow_factor = 25.0
    sim.set_target(UNIFIED, cfg.replicas)

    stop_flag = [False]
    walls: List[float] = []
    for cls, toks in (("interactive", 2), ("interactive", 2),
                      ("background", 8)):
        reqs = [Request(at=0.0, cls=cls, prompt_len=8, new_tokens=toks,
                        deadline_ms=120000.0)
                for _ in range(n_per_feeder)]
        sim.spawn_feeder(reqs, record=walls if cls == "interactive"
                         else None, stop=lambda: stop_flag[0])

    t0 = time.perf_counter()
    # Phase A — gray failure: run until the victim's breaker opens
    # (breakers on), or for a fixed traffic window (the CONTROL arm —
    # breakers disabled, the victim keeps serving 25x slow and the
    # interactive percentiles show it).
    breakers = sim.router.breakers
    if breakers is not None:
        eng.run(until=300.0,
                stop=lambda: victim.addr in breakers.open_addrs())
        victim_isolated = victim.addr in breakers.open_addrs()
        victim_trip_reason = breakers.describe().get(
            victim.addr, {}).get("reason", "")
    else:
        eng.run(until=eng.clock.now + 3.0)
        victim_isolated = False
        victim_trip_reason = ""
    victim_alive = victim.addr in [
        r.addr for r in sim.registry.alive()]

    # Deadline probes: long decodes against a far-too-short deadline
    # must answer deadline_exceeded at ~the deadline (in-batcher
    # cancel / router fail-fast), never a late completion.  Each probe
    # observes its OWN outcome through the item sink — under WFQ a
    # feeder may be the fiber that actually dispatches it.
    probe_outcomes: List[str] = []

    def probe_body() -> None:
        for _ in range(4):
            req = Request(at=0.0, cls="interactive", prompt_len=8,
                          new_tokens=400, deadline_ms=60.0)
            sink: list = []
            t_probe = eng.clock.now
            if not sim.submit(req, sink=sink):
                probe_outcomes.append("shed")
                continue
            while not sink:
                item = sim.admission.get(timeout=0)
                if item is not None:
                    sim.dispatch(item)
                else:
                    eng.sleep(0.002)
            reply, end = sink[0]
            kind = reply.get("kind") if isinstance(reply, dict) else None
            late = end > t_probe + 0.060 + 0.015
            probe_outcomes.append(
                "ok" if kind == "deadline_exceeded" and not late
                else f"violation:{kind}:{late}")

    eng.spawn(probe_body, name="sim-probe")
    eng.run(until=eng.clock.now + 10.0,
            stop=lambda: len(probe_outcomes) >= 4)

    # Phase B — hard churn: SIGKILL a healthy replica whole, then
    # hand-stepped REAL-autoscaler ticks with calm signals relaunch it
    # (crash self-heal through the warming state) — the exact shape of
    # the bench's phase B.
    doomed = next(r for r in reps if r is not victim and not r.down)
    sim.kill(doomed)
    calm = {"queue_wait_p99_ms": 0.0, "util": 0.5, "kv_headroom": None}
    auto = FleetAutoscaler(
        sim, dataclasses.replace(cfg.autoscaler, scale_up_cooldown=0.0,
                                 scale_down_cooldown=0.0),
        signals=lambda: {UNIFIED: dict(calm)}, clock=eng.clock)
    heal_deadline = eng.clock.now + 120.0
    while (sim.tier_actual(UNIFIED) < cfg.replicas
           or len(sim.registry.alive()) < cfg.replicas) \
            and eng.clock.now < heal_deadline:
        auto.step()
        eng.run(until=eng.clock.now + 0.1)
    healed = sim.tier_actual(UNIFIED) >= cfg.replicas \
        and len(sim.registry.alive()) >= cfg.replicas

    # One-shot link sever against a healthy replica: the router drops
    # the link and retries; the next beat revives the entry.
    other = next(r for r in sim.transport.replicas.values()
                 if not r.down and r is not victim)
    other.sever_next = 1

    # Phase C — blue-green rollout under the same traffic: v2 tier up
    # (warming -> alive), preference shift, drain-migrate-kill of v1.
    v1 = [r for r in sim.transport.replicas.values() if not r.down]
    v2 = [sim.add_replica(UNIFIED, weights_version="v2",
                          warm_s=cfg.warmup_s) for _ in range(3)]
    eng.run(until=eng.clock.now + 30.0,
            stop=lambda: sum(
                1 for r in sim.registry.alive()
                if r.weights_version == "v2") >= len(v2))
    sim.router.set_preferred_version("v2")
    for r in v1:
        sim.registry.begin_drain(r.addr, pinned=True)
        sim.request_migration(r.addr)
    eng.run(until=eng.clock.now + 2.0)
    for r in v1:
        if not r.down:
            sim.kill(r)

    # Drain the feeders to completion.
    eng.run(until=eng.clock.now + 600.0, stop=sim.drained)
    stop_flag[0] = True
    wall = time.perf_counter() - t0

    out = sim.results(wall)
    out.update({
        "victim": victim.addr,
        "victim_isolated": bool(victim_isolated),
        "victim_alive_while_isolated": bool(victim_alive),
        "victim_trip_reason": victim_trip_reason,
        "healed": bool(healed),
        "probe_outcomes": probe_outcomes,
        "probes_conformant": all(p == "ok" for p in probe_outcomes),
        "migration_reruns": sim.metrics.get("migration_reruns"),
        "migration_resumes": sim.metrics.get("migration_resumes"),
        "interactive_p99_ms": (sorted(walls)[
            max(0, int(0.99 * len(walls)) - 1)] if walls else None),
    })
    sim.stop()
    return out


class _LeanOpenWorkload:
    """Deterministic fixed-interval arrivals alternating the two
    default classes — the scale scenario's workload, built to add as
    little generator overhead as possible at 1M requests (no
    per-request distribution draws)."""

    def __init__(self, n_requests: int, rate: float):
        self.n_requests = int(n_requests)
        self.rate = float(rate)

    def __iter__(self):
        gap = 1.0 / self.rate
        t = 0.0
        a = Request(0.0, "interactive", 16, 8, None)
        b = Request(0.0, "background", 16, 8, None)
        for i in range(self.n_requests):
            t += gap
            yield (a if i & 1 else b)._replace(at=t)


def scenario_scale(overrides=(), n_requests: int = 1_000_000,
                   replicas: Optional[int] = None,
                   seed: Optional[int] = None,
                   workload=None, model_fit: Optional[dict] = None,
                   cfg: Optional[SimConfig] = None) -> Dict[str, Any]:
    """The scale proof: 1000 replicas, >= 1M requests, open Poisson
    arrivals — the ``bench_fleet_sim`` scenario (no deadlines, two
    classes, breakers on).  Exists to keep ``sim_events_per_sec``
    honest; shrink ``n_requests``/``replicas`` for smoke runs."""
    cfg = _new_cfg(cfg, overrides)
    cfg.replicas = int(replicas) if replicas is not None else 1000
    if seed is not None:
        cfg.seed = int(seed)
    if not any(p == "workers" for p, _ in (overrides or ())):
        # 64 dispatchers is the sweet spot measured for switch
        # overhead; the scenario measures control-plane scale (1000
        # registry entries, picks over the full tier), not pool width.
        cfg.workers = 64
    cfg.max_queue = 4096
    cfg.hb_interval = 1.0
    cfg.model = dataclasses.replace(cfg.model, jitter=0.0)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    sim = FleetSim(cfg)
    for _ in range(cfg.replicas):
        sim.add_replica(UNIFIED)
    if workload is None:
        _, per_req_s = cfg.model.service_s(16, 8, random.Random(0))
        # Arrivals at the dispatcher pool's saturation point (the pool
        # is the concurrency bound, same shape as the real gateway's
        # worker pool): the queue stays primed, so this measures peak
        # sustainable throughput — and never idles the pool.
        rate = cfg.workers / max(1e-9, per_req_s)
        workload = _LeanOpenWorkload(n_requests, rate)
    sim.feed(workload)
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    sim.stop()
    return out


def scenario_diurnal(overrides=(), n_requests: int = 1_000_000,
                     replicas: Optional[int] = None,
                     seed: Optional[int] = None,
                     workload=None, model_fit: Optional[dict] = None,
                     cfg: Optional[SimConfig] = None) -> Dict[str, Any]:
    """The million-user front door's day at 10x the scale proof:
    10,000 replicas, >= 1M requests riding a sinusoidal day/night
    envelope with seeded flash crowds (:class:`~tfmesos_tpu.fleet.
    workload.DiurnalWorkload` — fit the constants from a real
    ``tfserve trace --json`` export with ``fit_diurnal``), heartbeats
    SHARDED (``cfg.hb_shards``) so the event heap prices requests,
    not 10k timer pops per sim-second.  Byte-for-byte deterministic
    per seed; gateway counts, trader constants and admission bounds
    all sweepable.  Publishes ``sim_events_per_sec_10k`` — the
    10x-replica hot-path floor benched next to ``sim_events_per_sec``
    (the scale scenario's 45k events/s contract)."""
    cfg = _new_cfg(cfg, overrides)
    cfg.replicas = int(replicas) if replicas is not None else 10_000
    if seed is not None:
        cfg.seed = int(seed)
    if not swept(overrides, "workers"):
        cfg.workers = 64      # the scale scenario's measured sweet spot
    if not swept(overrides, "max_queue"):
        # ALIAS-AWARE guard (swept, not a raw path scan): a
        # ``--sweep admission.max_queue=...`` row must keep its bound
        # — the raw scan saw only "admission.max_queue" and silently
        # clobbered every row back to 4096.
        cfg.max_queue = 4096
    # A 10k fleet beats and sweeps SLOWER than a 3-replica one (real
    # fleets stretch liveness cadence with size): per-sim-second table
    # work is replicas/hb_interval observes plus a full-table sweep
    # every sweep_interval — at the scale scenario's cadence that is
    # 10k observes + 5 sweeps per sim-second of pure bookkeeping wall.
    # Each constant stays individually sweepable.
    for path, v in (("hb_interval", 5.0), ("suspect_after", 7.5),
                    ("dead_after", 15.0), ("evict_after", 60.0),
                    ("sweep_interval", 2.0)):
        if not swept(overrides, path):
            setattr(cfg, path, v)
    if cfg.hb_shards <= 0:
        # Per-replica beats are 2k heap events per sim-second of pure
        # timer churn at this scale; 64 shard beats carry the same
        # registry observations.
        cfg.hb_shards = 64
    cfg.model = dataclasses.replace(cfg.model, jitter=0.0)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    sim = FleetSim(cfg)
    # 10k one-line "registered" INFO records are pure handler wall (and
    # unreadable output) at bring-up — quiet the registry logger for
    # the bulk registration only.
    reg_log = logging.getLogger("tfmesos_tpu.fleet.registry")
    old_level = reg_log.level
    reg_log.setLevel(logging.WARNING)
    try:
        for _ in range(cfg.replicas):
            sim.add_replica(UNIFIED)
    finally:
        reg_log.setLevel(old_level)
    if workload is None:
        _, per_req_s = cfg.model.service_s(16, 8, random.Random(0))
        # MEAN arrivals at the dispatcher pool's saturation point (the
        # scale scenario's pump) so the envelope swings the pool from
        # trough slack to crest overload — two full day/night cycles
        # plus four flash crowds across the stream.
        peak = 4.0
        pump = cfg.workers / max(1e-9, per_req_s)
        base = pump / (1.0 + (peak - 1.0) / 2.0)
        span = n_requests / pump
        workload = DiurnalWorkload(
            n_requests, base, seed=cfg.seed,
            period_s=max(1.0, span / 2.0), peak_ratio=peak,
            bursts=4, burst_ratio=3.0,
            burst_duration_s=max(0.5, span / 50.0),
            class_mix={"interactive": 4.0, "background": 1.0},
            prompt_len=16, prompt_sigma=0.0,
            new_tokens=8, new_tokens_sigma=0.0)
    sim.feed(workload)
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out["sim_events_per_sec_10k"] = out.get("sim_events_per_sec")
    out["hb_shards"] = cfg.hb_shards
    sim.stop()
    return out


def scenario_offline_lane(overrides=(), n_requests: int = 3000,
                          replicas: Optional[int] = None,
                          seed: Optional[int] = None,
                          workload=None,
                          model_fit: Optional[dict] = None,
                          cfg: Optional[SimConfig] = None
                          ) -> Dict[str, Any]:
    """The OFFLINE lane (ROADMAP 6b, docs/SERVING.md "Offline lane"):
    interactive arrivals ride a diurnal envelope whose trough leaves
    decode slots idle, while a deadline-less batch backlog (half the
    interactive volume, submitted up front) fills them through the
    strict-priority batch class.  The tunable under sweep is the
    interactive-vs-batch budget split: ``--sweep batch_slot_frac=
    0.25,0.5,0.75,1.0`` prices reserve headroom against harvested
    utilization, and ``--sweep batch_lane=false,true`` is the
    lane-off baseline the bench asserts against (utilization strictly
    higher with the lane on, interactive p99 held, zero interactive
    requests lost)."""
    cfg = _new_cfg(cfg, overrides)
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if not swept(overrides, "batch_lane"):
        cfg.batch_lane = True
    if not swept(overrides, "max_queue"):
        # The batch backlog arrives up front BY DESIGN — it must fit
        # the bounded queue, or the scenario measures shed, not the
        # lane (the bound stays individually sweepable).
        cfg.max_queue = max(cfg.max_queue, n_requests)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.replicas * cfg.capacity))
    sim = FleetSim(cfg)
    for _ in range(cfg.replicas):
        sim.add_replica(UNIFIED)
    n_batch = n_requests // 2
    if workload is None:
        _, per_req_s = cfg.model.service_s(16, 8, random.Random(0))
        # Crest at ~0.9x the fleet's service rate: saturated enough
        # that the lane must yield, trough idle enough that there is
        # capacity to harvest.
        pump = cfg.replicas * cfg.capacity / max(1e-9, per_req_s)
        base = 0.45 * pump
        span = n_requests / (base * 1.5)
        workload = DiurnalWorkload(
            n_requests, base, seed=cfg.seed,
            period_s=max(1.0, span), peak_ratio=2.0,
            class_mix={"interactive": 1.0},
            prompt_len=16, prompt_sigma=0.0,
            new_tokens=8, new_tokens_sigma=0.0,
            deadline_ms=60_000.0)
    sim.feed(workload)
    if n_batch and cfg.batch_lane:
        # The backlog: deadline-less batch arrivals land in the first
        # slice of the day and wait for idle slots.  Lane OFF is the
        # no-offline-work baseline — without the class there is no
        # surface to submit it through.
        sim.feed(SyntheticWorkload(
            n_batch, rate=max(1.0, n_batch / 2.0),
            seed=cfg.seed + 1, class_mix={"batch": 1.0},
            prompt_len=16, prompt_sigma=0.0,
            new_tokens=8, new_tokens_sigma=0.0))
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out["batch_lane"] = cfg.batch_lane
    out["batch_slot_frac"] = cfg.batch_slot_frac
    out["batch_planned"] = n_batch if cfg.batch_lane else 0
    sim.stop()
    return out


def scenario_multi_gateway(overrides=(), n_requests: int = 6000,
                           replicas: Optional[int] = None,
                           seed: Optional[int] = None,
                           workload=None,
                           model_fit: Optional[dict] = None,
                           cfg: Optional[SimConfig] = None
                           ) -> Dict[str, Any]:
    """The multi-gateway front door at sim scale (`tfserve --gateways
    N`): arrivals spread round-robin over N gateway fronts sharing ONE
    registry/router view; mid-run one front is HARD-KILLED and its
    queued work fails over to the survivors (the client-replay analog)
    — the scenario asserts the fleet answers every planned request
    (zero lost) and reports per-front shed plus the failover count, so
    ROADMAP item-2 policy constants (per-front queue bounds, worker
    width) are sweepable at 1000-replica scale."""
    cfg = _new_cfg(cfg, overrides)
    if cfg.gateways < 2:
        # The scenario is ABOUT the multi-front topology: a lone front
        # has nothing to fail over to.  Loud, so a sweep row labeled
        # gateways=1 is never silently a 3-front run.
        if any(p == "gateways" for p, _ in (overrides or ())):
            raise ValueError(
                f"the multi-gateway scenario needs gateways >= 2 "
                f"(got {cfg.gateways}); sweep the steady scenario "
                f"for a single-front baseline")
        cfg.gateways = 3
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    # Per-front pools must jointly cover the fleet's concurrency even
    # AFTER one front dies: size each front's pool for the whole fleet
    # divided by the surviving fronts.
    cfg.workers = max(cfg.workers,
                      min(128, (2 * cfg.replicas * cfg.capacity)
                          // max(1, cfg.gateways - 1)))
    sim = FleetSim(cfg)
    for _ in range(cfg.replicas):
        sim.add_replica(UNIFIED)
    if workload is None:
        _, per_req_s = cfg.model.service_s(64, 16, random.Random(0))
        # Slightly OVER fleet capacity: queues stay primed, so the
        # killed front demonstrably holds work that must fail over
        # (an idle-queue kill would prove nothing).
        rate = 1.2 * cfg.replicas * cfg.capacity / max(1e-9, per_req_s)
        workload = SyntheticWorkload(
            n_requests=n_requests, seed=cfg.seed, rate=rate,
            class_mix={"interactive": 1.0, "background": 2.0},
            prompt_len=64, new_tokens=16)
    else:
        rate = getattr(workload, "rate", 100.0)
    sim.feed(workload)
    sim.start_workers()
    # SIGKILL one front door mid-traffic: at roughly the arrival
    # stream's midpoint.
    n = getattr(workload, "n_requests", n_requests)
    t_kill = 0.5 * n / max(1e-9, rate)
    killed_at: List[float] = []

    def kill() -> None:
        killed_at.append(sim.engine.clock.now)
        sim.kill_gateway(1)

    sim.engine.at(t_kill, kill)
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out.update({
        "gateways": len(sim.fronts),
        "gateway_killed_at": round(killed_at[0], 3) if killed_at
        else None,
        "gateway_failovers": sim.gateway_failovers,
        "per_front_shed": [f.admission.shed_counts()
                           for f in sim.fronts],
    })
    sim.stop()
    return out


class _SessionWorkload:
    """Multi-turn conversations as an open-arrival stream: ``sessions``
    concurrent conversations of ``turns`` turns each, every turn's
    prompt the FULL history so far (prior prompt + reply + the new
    user tokens) — the workload shape the KV tier exists for.  Turn
    rounds interleave across sessions (round-robin with Poisson gaps),
    so a session's turns never arrive back-to-back and the tier must
    actually hold the parked state across interleaved traffic."""

    def __init__(self, sessions: int, turns: int, rate: float,
                 seed: int = 0, user_tokens: int = 32,
                 reply_tokens: int = 16, cls: str = "interactive"):
        if sessions < 1 or turns < 1:
            raise ValueError(f"sessions ({sessions}) and turns "
                             f"({turns}) must be >= 1")
        self.sessions = int(sessions)
        self.turns = int(turns)
        self.rate = float(rate)
        self.seed = int(seed)
        self.user_tokens = int(user_tokens)
        self.reply_tokens = int(reply_tokens)
        self.cls = cls
        self.n_requests = self.sessions * self.turns

    def __iter__(self):
        rng = random.Random(self.seed)
        t = 0.0
        per_turn = self.user_tokens + self.reply_tokens
        for k in range(self.turns):
            plen = k * per_turn + self.user_tokens
            for s in range(self.sessions):
                t += rng.expovariate(self.rate)
                yield Request(at=t, cls=self.cls, prompt_len=plen,
                              new_tokens=self.reply_tokens,
                              session=f"s{s}")


def scenario_sessions(overrides=(), n_requests: Optional[int] = None,
                      replicas: Optional[int] = None,
                      seed: Optional[int] = None,
                      turns: int = 6, sessions: Optional[int] = None,
                      workload=None, model_fit: Optional[dict] = None,
                      cfg: Optional[SimConfig] = None
                      ) -> Dict[str, Any]:
    """Session park/resume at scale (docs/SERVING.md "KV tiering &
    sessions"): thousands of multi-turn conversations whose later
    turns resume from the host-shared KV tier and prefill only the new
    tail, with one replica HARD-KILLED mid-run — parked sessions
    survive it (the tier is host-shared, the disk-dir deployment) and
    keep resuming on the survivors.  Reports the tier hit rate and the
    mean resumed vs cold-turn TTFT; the regression contract (asserted
    in tests/test_sim.py): zero lost requests across the kill, and
    resumed turns strictly cheaper than cold full-history prefills."""
    cfg = _new_cfg(cfg, overrides)
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    # Long-history prefills are the cost the tier removes — make the
    # per-token prefill cost visible against the base.
    if not any(p.startswith("model.") for p, _ in (overrides or ())):
        cfg.model = dataclasses.replace(cfg.model,
                                        prefill_ms_per_token=0.2)
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.replicas * cfg.capacity))
    sim = FleetSim(cfg)
    # The cross-host placement knob (gang-parked sharded sessions):
    # below 1.0, a resume landing off the parker's host re-prefills
    # cold — sweep it to price host-local vs shared artifact stores.
    sim.transport.cross_host_resume = float(cfg.cross_host_resume)
    sim.transport.kv_replication = int(cfg.kv_replication)
    sim.transport.kv_forward_ms = float(cfg.kv_forward_ms)
    sim.transport.kv_placement = str(cfg.kv_placement)
    reps = [sim.add_replica(UNIFIED) for _ in range(cfg.replicas)]
    if workload is None:
        n_sessions = int(sessions) if sessions is not None else (
            max(1, int(n_requests) // max(1, turns))
            if n_requests is not None else 500)
        _, per_req_s = cfg.model.service_s(
            (turns // 2) * 48 + 32, 16, random.Random(0))
        rate = 0.6 * cfg.replicas * cfg.capacity / max(1e-9, per_req_s)
        workload = _SessionWorkload(n_sessions, turns, rate,
                                    seed=cfg.seed)
    sim.feed(workload)
    sim.start_workers()
    # Hard-kill one replica at roughly the stream's midpoint: parked
    # sessions must keep resuming on the survivors.
    n = getattr(workload, "n_requests", 0)
    rate = getattr(workload, "rate", 100.0)
    if len(reps) > 1 and n:
        sim.engine.at(0.5 * n / max(1e-9, rate),
                      lambda: sim.kill(reps[0]))
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    st = sim.transport.session_stats
    hits, misses = st["hits"], st["misses"]
    out.update({
        "session_tier": dict(st),
        "kv_tier_hit_rate": round(hits / max(1, hits + misses), 4),
        "sessions_parked": len(sim.transport.session_tier),
        "resumed_ttft_mean_ms": round(
            st["ttft_hit_ms"] / max(1, st["resume"]), 3),
        "cold_ttft_mean_ms": round(
            st["ttft_cold_ms"] / max(1, st["park"] - st["resume"]), 3),
        "cross_host_resume": cfg.cross_host_resume,
        "kv_replication": cfg.kv_replication,
        "kv_placement": cfg.kv_placement,
    })
    # The placement sweep's figure of merit: how evenly the K-way
    # copies landed across surviving tiers (max vs mean copies held).
    load = sim.transport._tier_load
    if load:
        out["kv_copy_load_max"] = max(load.values())
        out["kv_copy_load_mean"] = round(
            sum(load.values()) / len(load), 2)
    sim.stop()
    return out


def scenario_gang(overrides=(), n_requests: int = 4000,
                  replicas: Optional[int] = None,
                  seed: Optional[int] = None,
                  workload=None, model_fit: Optional[dict] = None,
                  cfg: Optional[SimConfig] = None) -> Dict[str, Any]:
    """Gang replicas at sim scale (docs/SERVING.md "Gang replicas"):
    a unified tier of N-member pod-slice gangs under steady open
    arrivals, with one gang MEMBER hard-killed mid-run — the gang
    dies whole, re-forms after ``gang_reform_s`` (rendezvous +
    re-warm), and its in-flight work replays on the survivors.  The
    regression contract (tests/test_sim.py): zero lost requests
    across the member kill, the fleet ends with the booted gang count
    again, and a gang fleet's decode tail beats the single-process
    fleet of equal replica count (that is what the slice buys).
    Sweep the slice shape with ``--sweep gang_size=2,4,8`` or the
    collective tax with ``--sweep gang_efficiency=0.6,0.85,1.0``."""
    cfg = _new_cfg(cfg, overrides)
    if replicas is not None:
        cfg.replicas = int(replicas)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    if cfg.gang_size <= 1:
        cfg.gang_size = 4
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.replicas * cfg.capacity))
    sim = FleetSim(cfg)
    reps = [sim.add_replica(UNIFIED, gang_size=cfg.gang_size)
            for _ in range(cfg.replicas)]
    if workload is None:
        # Rate from the SINGLE-PROCESS model: the same offered load a
        # non-gang fleet of this shape would see, so the gang's
        # speedup shows up as latency headroom, not as an easier run.
        _, per_req_s = cfg.model.service_s(64, 16, random.Random(0))
        workload = SyntheticWorkload(
            n_requests=n_requests, seed=cfg.seed,
            rate=0.7 * cfg.replicas * cfg.capacity
            / max(1e-9, per_req_s),
            class_mix={"interactive": 1.0, "background": 2.0},
            prompt_len=64, new_tokens=16)
    sim.feed(workload)
    sim.start_workers()
    n = getattr(workload, "n_requests", 0)
    rate = getattr(workload, "rate", 100.0)
    if n:
        # Mid-stream member SIGKILL: the gang death + whole re-form.
        sim.engine.at(0.5 * n / max(1e-9, rate),
                      lambda: sim.kill_gang_member(reps[0]))
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    if n:
        # Let the re-form land (it may trail the last arrival): the
        # scenario's contract is that the fleet ENDS whole again.
        sim.engine.run(until=sim.engine.clock.now + cfg.gang_reform_s
                       + cfg.warmup_s + 3 * cfg.hb_interval)
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out.update({
        "gang_size": cfg.gang_size,
        "gang_efficiency": cfg.gang_efficiency,
        "gang_reform_s": cfg.gang_reform_s,
        "gang_deaths": sim.metrics.get("gang_deaths"),
        "gang_reforms": sim.metrics.get("gang_reforms"),
        "gangs_actual": sim.tier_actual(UNIFIED),
        "gang_summary": sim.registry.gang_summary(),
    })
    sim.stop()
    return out


def scenario_multi_model(overrides=(), n_requests: int = 24000,
                         replicas: Optional[int] = None,
                         seed: Optional[int] = None,
                         workload=None,
                         model_fit: Optional[dict] = None,
                         cfg: Optional[SimConfig] = None
                         ) -> Dict[str, Any]:
    """The model catalog at sim scale (docs/SERVING.md "Model
    catalog"): skewed two-model traffic whose hotness FLIPS mid-run
    against a fixed fleet-wide replica budget, plus one idle model and
    a warm pool.  The REAL :class:`~tfmesos_tpu.fleet.catalog.
    ModelTrader` must (a) scale the idle model to zero (freeing its
    budget slot), (b) TRADE replicas from the cooling model to the
    heating one after the flip — without thrashing them back and
    forth — and (c) cold-start the zeroed model through the warm pool
    when a late request demands it.  The regression contract
    (tests/test_sim.py): the post-flip hot model ends with MORE
    replicas than it booted, trades stay bounded, the cold start
    completes, zero lost requests — deterministic per seed.  Sweep the
    trading constants with ``--sweep trader.zero_after_ticks=4,8,16``
    or ``--sweep trader.trade_cooldown_s=0,5,20``."""
    cfg = _new_cfg(cfg, overrides)
    if seed is not None:
        cfg.seed = int(seed)
    if model_fit:
        for k, v in model_fit.items():
            if hasattr(cfg.model, k):
                setattr(cfg.model, k, v)
    if not cfg.models:
        cfg.models = (("alpha", 3), ("beta", 1), ("gamma", 1))
    if replicas is not None:
        # --replicas scales the FIRST (hot) model's boot count.
        first = cfg.models[0]
        cfg.models = ((first[0], int(replicas)),) + cfg.models[1:]
    if cfg.warm_pool == 0:
        cfg.warm_pool = 1
    boot = sum(n for _, n in cfg.models)
    if cfg.model_budget is None:
        cfg.model_budget = boot + cfg.warm_pool
    # Trading reacts at the tick cadence; the scenario's phases span
    # tens of virtual seconds, so the default cooldowns fit.
    cfg.autoscale = True
    cfg.workers = max(cfg.workers,
                      min(256, 2 * cfg.model_budget * cfg.capacity))
    sim = FleetSim(cfg)
    catalog = ModelCatalog([
        ModelSpec(mid, replicas=n, seed=i)
        for i, (mid, n) in enumerate(cfg.models)])
    for i, (mid, n) in enumerate(cfg.models):
        key = model_key(mid)
        sim.set_target(key, n)
        for _ in range(n):
            sim.launch_replica(key)
    from tfmesos_tpu.fleet.catalog import POOL_KEY
    sim.set_target(POOL_KEY, cfg.warm_pool)
    for _ in range(cfg.warm_pool):
        sim.launch_replica(POOL_KEY)
    sim.enable_trader(catalog)
    hot, cold = cfg.models[0][0], cfg.models[1][0]
    idle = cfg.models[2][0] if len(cfg.models) > 2 else None
    if workload is None:
        _, per_req_s = cfg.model.service_s(64, 16, random.Random(0))
        # Saturate the HOT model's boot allocation so its pressure is
        # unambiguous; the cold model idles along at a trickle.
        hot_rate = 1.1 * cfg.models[0][1] * cfg.capacity \
            / max(1e-9, per_req_s)
        cold_rate = 0.1 * hot_rate
        n_half = n_requests // 2
        mk = SyntheticWorkload
        phase1 = [
            mk(n_requests=int(n_half * 0.9), seed=cfg.seed,
               rate=hot_rate, prompt_len=64, new_tokens=16,
               model=hot),
            mk(n_requests=max(1, int(n_half * 0.1)), seed=cfg.seed + 1,
               rate=cold_rate, prompt_len=64, new_tokens=16,
               model=cold),
        ]
        t_flip = max(max(r.at for r in w) for w in phase1)
        phase2 = [
            mk(n_requests=int(n_half * 0.9), seed=cfg.seed + 2,
               rate=hot_rate, prompt_len=64, new_tokens=16,
               model=cold, start_at=t_flip),
            mk(n_requests=max(1, int(n_half * 0.1)), seed=cfg.seed + 3,
               rate=cold_rate, prompt_len=64, new_tokens=16,
               model=hot, start_at=t_flip),
        ]
        for w in phase1 + phase2:
            sim.feed(w)
    else:
        t_flip = None
        sim.feed(workload)
    sim.start_workers()
    t0 = time.perf_counter()
    sim.engine.run(stop=sim.drained)
    # Allocation is read the moment traffic drains — before idleness
    # scales everything back to zero.
    post_flip_hot_actual = sim.tier_actual(model_key(cold))
    # COLD START: one late request for the scaled-to-zero idle model
    # must route through the demand hook -> warm-pool adoption and
    # COMPLETE, never error.
    cold_start: Dict[str, Any] = {}
    if idle is not None:
        sink: list = []
        req = Request(at=0.0, cls=None, prompt_len=16, new_tokens=4,
                      model=idle)
        t_demand = sim.engine.clock.now

        def probe() -> None:
            if not sim.submit(req, sink=sink):
                return
            while not sink:
                item = sim.admission.get(timeout=0)
                if item is not None:
                    sim.dispatch(item)
                else:
                    sim.engine.sleep(0.01)

        sim.engine.spawn(probe, name="sim-cold-start")
        sim.engine.run(until=sim.engine.clock.now + 60.0,
                       stop=lambda: bool(sink))
        reply = sink[0][0] if sink else None
        cold_start = {
            "completed": bool(isinstance(reply, dict)
                              and reply.get("op") == "completion"),
            "wait_s": round(sim.engine.clock.now - t_demand, 3),
        }
    wall = time.perf_counter() - t0
    out = sim.results(wall)
    out.update({
        "hot_then_cold": (hot, cold),
        "flip_at": round(t_flip, 3) if t_flip is not None else None,
        "trades": sim.metrics.get("model_trades"),
        "trade_blocked": sim.metrics.get("model_trade_blocked"),
        "scale_to_zero": sim.metrics.get("model_scale_to_zero"),
        "adoptions": sim.metrics.get("sim_adoptions"),
        "cold_starts": sim.metrics.get("model_cold_starts"),
        "final_actual": {mid: sim.tier_actual(model_key(mid))
                         for mid, _ in cfg.models},
        "post_flip_hot_actual": post_flip_hot_actual,
        "pool_actual": sim.tier_actual(POOL_KEY),
        "budget": cfg.model_budget,
        "cold_start": cold_start,
    })
    sim.stop()
    return out


SCENARIOS: Dict[str, Callable[..., Dict[str, Any]]] = {
    "steady": scenario_steady,
    "surge": scenario_surge,
    "soak-replay": scenario_soak_replay,
    "scale": scenario_scale,
    "diurnal": scenario_diurnal,
    "offline-lane": scenario_offline_lane,
    "multi-gateway": scenario_multi_gateway,
    "sessions": scenario_sessions,
    "multi-model": scenario_multi_model,
    "gang": scenario_gang,
}


def run_scenario(name: str, overrides=(), **kwargs) -> Dict[str, Any]:
    """Run one named scenario with ``(path, value)`` overrides."""
    fn = SCENARIOS.get(name)
    if fn is None:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(have: {', '.join(sorted(SCENARIOS))})")
    return fn(overrides=overrides, **kwargs)


def run_sweep(name: str, path: str, values, overrides=(),
              **kwargs) -> List[Tuple[str, Dict[str, Any]]]:
    """Run ``name`` once per sweep value (each on the same seed, so
    rows differ only by the swept constant); returns ``[(value,
    results)]`` for the CLI's comparison table."""
    out = []
    for v in values:
        res = run_scenario(name,
                           overrides=list(overrides) + [(path, v)],
                           **kwargs)
        out.append((str(v), res))
    return out
