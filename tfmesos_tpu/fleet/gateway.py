"""The fleet's TCP front door.

The I/O plane is a :class:`~tfmesos_tpu.wire.WireServer` — ONE
selector-driven event loop carries every client connection (accept,
incremental Framer reads, buffered non-blocking writes), which is what
lifts the concurrent-connection ceiling from "one OS thread per client"
to "one fd per client" (docs/SERVING.md "Front-door scaling").  Request
EXECUTION keeps the worker-pool handoff: the loop-thread handler only
applies admission control (a shed costs one queue check, not a
dispatcher slot) and admitted requests wait in the bounded ingress
queue for one of ``workers`` dispatcher threads, which route them to a
replica and relay the completion back through the connection's
thread-safe buffered ``send``.

A fleet may run N gateways over ONE shared registry/router view
(``tfserve --gateways N``): each is stateless — any gateway can serve
any client — and registers its address for the ``gateways`` discovery
op, which clients use to find failover targets
(:class:`~tfmesos_tpu.fleet.client.FleetClient` replays idempotent
in-flight requests on a survivor when its gateway dies mid-stream).

Wire surface (all frames HMAC-authenticated with the cluster token):

* ``{"op": "generate", "id", "prompt", "max_new_tokens", "stop_token",
  "priority", "deadline_ms", "stream"}`` → ``{"op": "completion", "id",
  "tokens", "ttft_ms", "total_ms"}`` or ``{"op": "error", "id", "kind",
  "error"}`` with ``kind`` one of ``overloaded`` / ``rate_limited``
  (admission shed — back off), ``unavailable`` (no replica within the
  retry budget), ``bad_request``, ``deadline_exceeded`` (the request's
  end-to-end budget ran out — shed in the admission queue, failed fast
  by the router, or cancelled inside a replica's batcher; never
  retried).  ``deadline_ms`` (optional) is the request's END-TO-END
  budget in milliseconds from gateway receipt: the gateway stamps an
  absolute deadline, the WFQ queues shed expired work before dispatch,
  the router slices the remainder across its phases, and the replica's
  batcher cancels an expired resident row and frees its pages — no
  deadline preserves the flat ``request_timeout`` behavior exactly
  (docs/SERVING.md "Deadlines & failure containment").
  ``priority`` (optional; ``tenant`` is an alias) is
  the CLASS LABEL: it selects the weighted-fair admission queue the
  request waits in, and the class's preemption rank rides to the
  replica so a higher class can suspend lower-class resident rows under
  allocation pressure (docs/SERVING.md "Priorities, preemption &
  migration").  Unlabeled requests take the first-listed (default)
  class.
  ``stream`` (optional) asks for PER-TOKEN incremental replies: the
  completion's tokens are flushed as the replica's batcher emits them,
  as interleaved ``{"op": "tokens", "id", "off", "tokens"}`` frames
  (``off`` = tokens already streamed — the de-dup key across retries
  and failovers), followed by the usual final completion carrying the
  FULL list.  Old clients that never set it see exactly the old
  one-reply protocol.
  ``trace`` (optional) asks for FULL span detail on this request's
  trace: ``true`` under a gateway-minted id, a string to supply the
  trace id; every request gets an always-on summary trace regardless,
  and every reply (completion or error) carries its ``trace_id`` —
  fetch the waterfall later with the ``trace`` op (docs/SERVING.md
  "Observability").
* ``{"op": "metrics", "id"}`` → ``{"op": "metrics", "id", "snapshot"}``.
* ``{"op": "gateways", "id"}`` → ``{"op": "gateways", "id",
  "gateways": [addr, ...]}`` — the registered front doors of this
  fleet (client-side discovery for multi-gateway failover;
  ``tfserve gateways``).
* ``{"op": "trace", "id", "trace_id"? | "slowest": N? | "failed":
  true?, "limit"?}`` → ``{"op": "trace", "id", "traces": [...]}`` —
  one trace by id (full record), the N slowest, the newest failures,
  or the recent summaries (``tfserve trace``).
* ``{"op": "ping", "id"}`` → ``{"op": "pong", "id"}``.
* ``{"op": "rollout", "id", "weights_version"}`` → ``{"op": "rollout",
  "id", "ok": true, ...}`` or ``{"op": "error", "id", "kind":
  "rollout_failed" | "bad_request", "error"}`` — the blue-green weight
  rollout control op (``tfserve rollout``), served only when a fleet
  control plane is attached (``rollout_fn``); runs on its own thread
  and replies when the rollout completes or aborts.

Clients multiplex: many requests may be in flight per connection, and
completions return in FINISH order, matched by ``id`` — the same
streaming shape the replicas themselves speak.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import (AdmissionController,
                                         DeadlineExceeded, Overloaded,
                                         RateLimited)
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.router import Router
from tfmesos_tpu.fleet.tracing import TraceBook
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["Gateway"]


class Gateway:
    """Accepts streaming requests, admits, routes, relays completions."""

    def __init__(self, router: Router, admission: AdmissionController,
                 metrics: FleetMetrics, token: str = "",
                 host: str = "127.0.0.1", port: int = 0, workers: int = 8,
                 registry=None, tracebook: Optional[TraceBook] = None,
                 clock=time.monotonic, close_router: bool = True):
        self.router = router
        self.admission = admission
        self.metrics = metrics
        # The deadline time base.  Injectable, and shared with the
        # router/admission clocks by the caller: the absolute deadline
        # stamped here is compared against the SAME clock at every
        # later checkpoint (WFQ shed, router loop head, timeout
        # slices) — stamping from a different clock than the checks
        # read would silently stretch or shrink every budget.
        self._clock = clock
        # Request tracing is on-by-default at SUMMARY level (every
        # request finishes into the book); span DETAIL is tail-retained
        # per the book's sample/slow/failure rules (docs/SERVING.md
        # "Observability").
        self.tracebook = tracebook if tracebook is not None else TraceBook()
        self.token = token
        self.host = host
        self.port = int(port)
        self.workers = int(workers)
        self.registry = registry if registry is not None else router.registry
        # N gateways share ONE router; only the last one standing may
        # close it.  False = the fleet launcher owns the router's
        # lifecycle (multi-gateway); True (default) keeps the
        # single-gateway teardown of old.
        self._close_router = bool(close_router)
        self.log = get_logger("tfmesos_tpu.fleet.gateway")
        self.addr: Optional[str] = None
        # The fleet control plane's rollout entry point (set by
        # FleetServer after bring-up): callable(version) -> info dict,
        # raising on abort.  None = this gateway has no rollout surface.
        self.rollout_fn = None
        # Model catalog (docs/SERVING.md "Model catalog"), both set by
        # FleetServer on catalog fleets: the catalog resolves/validates
        # the request's ``model`` label (absent -> the default entry;
        # unknown -> bad_request), and swap_adapter_fn is the adapter
        # hot-swap control plane (callable(model_id, version, meta,
        # body) -> info dict).  None = model-less fleet: a ``model``
        # label is charset-checked and forwarded as-is.
        self.catalog = None
        self.swap_adapter_fn = None
        self._server: Optional[wire.WireServer] = None
        self._stop = threading.Event()
        self._threads = []
        self.killed = False
        metrics.register_gauge("queue_depth", admission.depth)
        # Per-class depths: under a background flood the operator must
        # be able to see WHICH class is backed up (one global depth
        # reads as "overloaded" even while interactive sails through).
        metrics.register_gauge("queue_depths", admission.class_depths)
        metrics.register_gauge("replicas_alive",
                               lambda: len(self.registry.alive()))
        # Replicas registered but still compiling (--warmup): present
        # in the table, invisible to every router tier — surfaced so an
        # operator can tell "warming fleet" from "missing replicas".
        metrics.register_gauge("replicas_warming",
                               lambda: len(self.registry.warming()))
        # Per-role replica counts + aggregate outstanding/headroom, so
        # a disaggregated deployment's snapshot shows each tier served.
        metrics.register_gauge("roles", self.registry.role_summary)
        # Failure containment (docs/SERVING.md "Deadlines & failure
        # containment"): breaker state and the retry-budget level are
        # the on-call's first two questions during a brown-out, so they
        # ride the snapshot AND the periodic report line.
        metrics.register_gauge("breakers", router.breaker_summary)
        metrics.register_gauge("retry_budget", router.retry_budget_level)
        # Trace book occupancy + lifetime finish/detail counts — the
        # "is tracing actually retaining anything" sanity gauge.
        metrics.register_gauge("traces", self.tracebook.describe)
        # Fleet-wide KV-tier aggregate (summed per-replica heartbeat
        # counters: hits/misses/spills/promotions/park/resume + tier
        # occupancy) — the memory-hierarchy gauge, flattened into the
        # Prometheus exposition like every dict gauge.
        if hasattr(self.registry, "kv_tier_summary"):
            metrics.register_gauge("kv_tier",
                                   self.registry.kv_tier_summary)
        # Speculative decoding fleet-wide: replicas serving with a
        # draft and their aggregate acceptance rate — the biggest
        # single-stream latency lever's health number, visible through
        # `tfserve metrics` and Prometheus like every dict gauge.
        if hasattr(self.registry, "spec_summary"):
            metrics.register_gauge("spec", self.registry.spec_summary)
        # Per-model replica counts + adapter-version distribution (the
        # model catalog's membership gauge).
        if hasattr(self.registry, "model_summary"):
            metrics.register_gauge("models",
                                   self.registry.model_summary)
        # Gang replicas fleet-wide: gang count, member slots, joined
        # members, and degraded gangs (fewer joined than the mesh
        # needs) — flat numerics, so the Prometheus exposition carries
        # every field.
        if hasattr(self.registry, "gang_summary"):
            metrics.register_gauge("gangs", self.registry.gang_summary)
        # Items that expired while queued still owe the client an
        # explicit answer — the controller hands them back here from
        # whichever worker's get() swept them.
        admission.on_expired = self._queue_expired

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Gateway":
        self._server = wire.WireServer(
            self._handle, token=self.token, host=self.host,
            port=self.port, name="gateway",
            advertise_host=(None if self.host in ("0.0.0.0", "::")
                            else self.host)).start()
        self.addr = self._server.addr
        self.log.info("fleet gateway listening on %s (%d workers, queue "
                      "bound %d, event-loop I/O)", self.addr,
                      self.workers, self.admission.max_queue)
        self._threads = []
        for i in range(self.workers):
            w = threading.Thread(target=self._worker, name=f"gateway-w{i}",
                                 daemon=True)
            w.start()
            self._threads.append(w)
        # Register this front door for client-side discovery (the
        # `gateways` op): stateless gateways over one registry view are
        # interchangeable, so any of them can hand out the full set.
        if hasattr(self.registry, "register_gateway"):
            self.registry.register_gateway(self.addr)
        return self

    def stop(self) -> None:
        """Graceful stop: deregister from discovery, close the event
        loop (clients see EOF), join the workers."""
        if hasattr(self.registry, "unregister_gateway") \
                and self.addr is not None:
            self.registry.unregister_gateway(self.addr)
        self._shutdown()
        if self._close_router:
            self.router.close()

    def kill(self) -> None:
        """Abrupt death (the bench's gateway 'SIGKILL'): connections
        slam shut mid-stream, nothing deregisters — exactly what peers
        of a SIGKILLed process observe.  The shared router/registry are
        untouched (they belong to the surviving gateways)."""
        self.killed = True
        self._shutdown()

    def _shutdown(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    # -- ingress (runs on the event-loop thread: admit, never block) -------

    def _handle(self, client: "wire.WireConn", msg: Any) -> None:
        # Raw frames never reach here: the gateway's WireServer rejects
        # the raw bit at the length prefix (allow_raw defaults False),
        # which both keeps the public port's pre-auth buffering bound
        # at MAX_FRAME and fails a misdirected call_raw fast
        # (connection drop, never a timeout hang).
        if not isinstance(msg, dict):
            return
        op = msg.get("op")
        cid = msg.get("id")
        if op == "ping":
            client.send({"op": "pong", "id": cid})
            return
        if op == "metrics":
            client.send({"op": "metrics", "id": cid,
                         "snapshot": self.metrics.snapshot()})
            return
        if op == "gateways":
            reg = self.registry
            if hasattr(reg, "gateway_addrs"):
                addrs = reg.gateway_addrs()
            else:
                addrs = [self.addr] if self.addr else []
            client.send({"op": "gateways", "id": cid,
                         "gateways": addrs})
            return
        if op == "trace":
            # Authenticated read of the trace book: one trace by id,
            # the N slowest, the N newest failures, or the recent
            # summaries — the `tfserve trace` surface.
            book = self.tracebook
            limit = msg.get("limit")
            limit = int(limit) if isinstance(limit, (int, float)) \
                and not isinstance(limit, bool) and limit > 0 else 20
            tid = msg.get("trace_id")
            if isinstance(tid, str) and tid:
                rec = book.get(tid)
                traces = [rec] if rec is not None else []
            elif msg.get("failed"):
                traces = book.failed(limit)
            elif msg.get("slowest"):
                n = msg.get("slowest")
                traces = book.slowest(int(n) if isinstance(n, (int, float))
                                      and not isinstance(n, bool)
                                      and n > 0 else 5)
            else:
                traces = book.recent(limit)
            client.send({"op": "trace", "id": cid, "traces": traces})
            return
        if op == "rollout":
            fn = self.rollout_fn
            version = msg.get("weights_version")
            if fn is None:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "no rollout control plane attached "
                                      "to this gateway"})
                return
            if not isinstance(version, str) or not version:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "rollout needs a non-empty "
                                      "weights_version"})
                return

            def run_rollout() -> None:
                # Off the event-loop thread: a rollout takes as long as
                # a fleet's worth of warmups and drains, and blocking
                # here would stall EVERY connection, not just one.
                try:
                    info = fn(version)
                except Exception as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "rollout_failed",
                                 "error": str(e)})
                    return
                out = {"op": "rollout", "id": cid, "ok": True,
                       "weights_version": version}
                if isinstance(info, dict):
                    out.update(info)
                client.send(out)

            threading.Thread(target=run_rollout, name="gateway-rollout",
                             daemon=True).start()
            return
        if op == "swap_adapter":
            # Adapter hot-swap control op (docs/SERVING.md "Model
            # catalog").  The public port rejects raw frames at the
            # length prefix, so the delta arrives base64 in JSON and
            # the control plane re-ships it to the replicas as raw
            # HMAC frames.  Validation here is an INGRESS boundary:
            # model_id/adapter_version are charset-checked before they
            # touch anything.
            from tfmesos_tpu.fleet.catalog import decode_adapter_fields
            from tfmesos_tpu.fleet.registry import validate_model_id

            fn = self.swap_adapter_fn
            if fn is None:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "no model catalog attached to "
                                      "this gateway"})
                return
            try:
                model_id = validate_model_id(msg.get("model_id"))
                version = validate_model_id(msg.get("adapter_version"))
                meta, body = decode_adapter_fields(msg.get("delta"))
            except (TypeError, ValueError) as e:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request", "error": str(e)})
                return

            def run_swap() -> None:
                # Off the event-loop thread: the swap waits for every
                # replica's in-flight generations to finish on the old
                # delta, and blocking here would stall EVERY
                # connection.
                try:
                    info = fn(model_id, version, meta, body)
                except KeyError as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "bad_request",
                                 "error": str(e)})
                    return
                except Exception as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "swap_failed",
                                 "error": str(e)})
                    return
                out = {"op": "swap_adapter", "id": cid, "ok": True}
                if isinstance(info, dict):
                    out.update(info)
                client.send(out)

            threading.Thread(target=run_swap, name="gateway-swap",
                             daemon=True).start()
            return
        if op != "generate":
            client.send({"op": "error", "id": cid, "kind": "bad_request",
                         "error": f"unknown op {op!r}"})
            return
        self.metrics.inc("received")
        # Tracing begins at receipt: a client-supplied string is the
        # trace id (and asks for full detail), any other truthy value
        # asks for detail under a gateway-minted id, absence still gets
        # the always-on summary + tail-based retention.
        traw = msg.get("trace")
        tr = self.tracebook.begin(
            trace_id=traw if isinstance(traw, str) and traw else None,
            want_detail=bool(traw))
        # The class label ("priority"; "tenant" is an alias) picks the
        # weighted-fair admission queue; the class's preemption RANK —
        # not the label — rides to the replica, so batcher-side
        # preemption and gateway-side fair-share stay one coherent
        # policy defined in one place (the class table).
        label = msg.get("priority")
        if not isinstance(label, str):
            label = msg.get("tenant")
        spec = self.admission.resolve(
            label if isinstance(label, str) else None)
        # The model tier (docs/SERVING.md "Model catalog"): the label
        # is charset-validated at THIS ingress (it reaches Prometheus
        # metric names and the routing filter), resolved against the
        # catalog when one is attached — absent rides the default
        # entry, unknown is an explicit bad_request (there are no
        # weights to serve it, and billing it to the default would be
        # silently wrong).  Model-less fleets forward a validated
        # label as-is and route by exact replica match.
        from tfmesos_tpu.fleet.registry import MODEL_ID_RE

        mraw = msg.get("model")
        model = None
        if mraw is not None:
            if not (isinstance(mraw, str)
                    and MODEL_ID_RE.fullmatch(mraw)):
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "bad_request", cls=spec.name)
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": f"invalid model label {mraw!r}",
                             "trace_id": tr.trace_id})
                return
            model = mraw
        if self.catalog is not None:
            try:
                model = self.catalog.resolve(model)
            except KeyError as e:
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "bad_request", cls=spec.name)
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request", "error": str(e),
                             "trace_id": tr.trace_id})
                return
        prompt = msg.get("prompt")
        tr.event("gateway", "recv", cls=spec.name, rank=spec.rank,
                 model=model or "",
                 prompt_len=(len(prompt)
                             if isinstance(prompt, (list, tuple)) else 0))
        # End-to-end deadline: the client ships a RELATIVE budget
        # (clocks do not agree across hosts); the gateway stamps the
        # absolute expiry the whole serving path measures against.
        # A malformed or non-positive value costs the field, never the
        # request — no deadline is today's flat-timeout behavior.
        dl = msg.get("deadline_ms")
        deadline = None
        if isinstance(dl, (int, float)) and not isinstance(dl, bool) \
                and dl > 0:
            deadline = self._clock() + float(dl) / 1000.0
        forward = {"op": "generate", "prompt": msg.get("prompt"),
                   "max_new_tokens": msg.get("max_new_tokens"),
                   "stop_token": msg.get("stop_token"),
                   "priority": spec.rank,
                   # Internal (stripped before the wire, like
                   # "deadline"): the router records its attempts here
                   # and stitches replica hop spans back in.
                   "_trace": tr}
        if msg.get("stream"):
            # Per-token streaming: the flag rides to the replica (whose
            # batcher flushes token frames per block) and the worker
            # installs the de-duplicating relay at dispatch.
            forward["stream"] = True
        sid = msg.get("session")
        if isinstance(sid, str) and sid:
            # Multi-turn session label (docs/SERVING.md "KV tiering &
            # sessions"): the router steers it at the replica holding
            # the parked KV, and the replica's batcher parks/resumes
            # under it.  Malformed values cost the field.
            forward["session"] = sid
        if model is not None:
            # Internal like "deadline"/"_trace": the router's model
            # tier filters on it (and re-stamps it onto the wire as
            # ``model`` for the replica's own cross-check).
            forward["_model"] = model
        if deadline is not None:
            forward["deadline"] = deadline
        try:
            self.admission.admit((client, cid, forward,
                                  time.perf_counter(), spec.name, tr),
                                 cls=spec.name, deadline=deadline,
                                 model=model)
        except DeadlineExceeded as e:
            self.metrics.inc("shed_deadline")
            self.metrics.inc(f"shed_deadline_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        except RateLimited as e:
            self.metrics.inc("shed_rate_limited")
            self.metrics.inc(f"shed_rate_limited_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        except Overloaded as e:
            self.metrics.inc("shed_queue")
            self.metrics.inc(f"shed_queue_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        else:
            self.metrics.inc("admitted")
            tr.event("admission", "enqueue", cls=spec.name)

    def _queue_expired(self, item) -> None:
        """One admitted request expired while waiting in its class
        queue (AdmissionController.get shed it before dispatch): the
        client still gets its explicit answer, and the books stay
        consistent — it was admitted, so it counts as failed too."""
        client, cid, _forward, t_enq, cls, tr = item
        self.metrics.inc("shed_deadline")
        self.metrics.inc(f"shed_deadline_{cls}")
        self.metrics.inc("failed")
        tr.add("admission", "queue_wait", tr.rel_ms(t_enq),
               (time.perf_counter() - t_enq) * 1000.0, cls=cls,
               expired=True)
        self.tracebook.finish(tr, "deadline_exceeded", cls=cls,
                              where="queued")
        client.send({"op": "error", "id": cid,
                     "kind": "deadline_exceeded",
                     "error": "request deadline expired while queued "
                              "at the gateway",
                     "trace_id": tr.trace_id})

    # -- dispatch ----------------------------------------------------------

    def _stream_relay(self, client: "wire.WireConn", cid):
        """The per-request partial-frame relay: forwards each replica
        ``tokens`` frame to the client re-keyed to ITS request id,
        de-duplicated by stream offset — a retried/resumed attempt
        re-streams from 0 (deterministic completions), and only tokens
        past the high-water mark go out, each exactly once."""
        sent = [0]

        def emit(frame) -> None:
            if isinstance(frame, wire.RawFrame):
                return              # never a token frame
            toks = frame.get("tokens")
            if not isinstance(toks, list) or not toks:
                return
            off = frame.get("off")
            off = int(off) if isinstance(off, (int, float)) \
                and not isinstance(off, bool) else 0
            prev = sent[0]
            new = toks[max(0, prev - off):]
            if not new or off + len(toks) <= prev:
                return
            sent[0] = off + len(toks)
            self.metrics.inc("stream_chunks")
            client.send({"op": "tokens", "id": cid,
                         "off": prev, "tokens": new})

        return emit

    def _worker(self) -> None:
        while not self._stop.is_set():
            item = self.admission.get(timeout=0.2)
            if item is None:
                continue
            client, cid, forward, t_enq, cls, tr = item
            # Queue wait is ITS OWN histogram, never folded into TTFT:
            # TTFT measures the serving path (prefill + transfer), and
            # conflating admission backlog with it would mask exactly
            # the stalls disaggregation removes.  The per-class variant
            # is what the priority bench (and an SLO dashboard) reads —
            # the global one stays the autoscaler's signal.
            wait_ms = (time.perf_counter() - t_enq) * 1000.0
            self.metrics.observe("queue_wait_ms", wait_ms)
            self.metrics.observe(f"queue_wait_ms_{cls}", wait_ms)
            model = forward.get("_model")
            if model:
                # The per-MODEL queue-wait histogram is the model
                # trader's relative-pressure signal (windowed p99 per
                # model is what decides who trades replicas to whom).
                self.metrics.observe(f"queue_wait_ms_model_{model}",
                                     wait_ms)
            # The WFQ dequeue closes the queue-wait span — the first
            # hop of every waterfall.
            tr.add("admission", "queue_wait", tr.rel_ms(t_enq), wait_ms,
                   cls=cls)
            if forward.get("stream"):
                forward["_emit"] = self._stream_relay(client, cid)
            try:
                reply = self.router.route(forward)
            except Exception as e:
                # Any routing failure (RoutingError or unexpected)
                # becomes an explicit client error; a gateway worker
                # must survive everything.
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "unavailable", cls=cls,
                                      error=str(e)[:200])
                client.send({"op": "error", "id": cid,
                             "kind": "unavailable", "error": str(e),
                             "trace_id": tr.trace_id})
                continue
            out = dict(reply) if isinstance(reply, dict) else {
                "op": "error", "kind": "internal",
                "error": f"malformed replica reply {reply!r}"}
            out["id"] = cid
            out["trace_id"] = tr.trace_id
            # Belt-and-braces: the router absorbs piggybacked replica
            # spans into the trace and pops them, but a reply that
            # bypassed absorption must not leak span payloads to the
            # client.
            out.pop("trace", None)
            if out.get("op") == "completion":
                self.metrics.inc("completed")
                n_out = len(out.get("tokens") or ())
                self.metrics.inc("tokens_out", n_out)
                # Billing-grade metering: prompt and decode tokens per
                # tenant-class x model (docs/SERVING.md "Model
                # catalog").  Plain counters, so they ride the
                # snapshot AND the Prometheus exposition (names
                # sanitized there); counted only on DELIVERED
                # completions — failed work is not billable.
                suffix = f"{cls}_{model}" if model else cls
                self.metrics.inc(f"metering_prompt_tokens_{suffix}",
                                 len(forward.get("prompt") or ()))
                self.metrics.inc(f"metering_decode_tokens_{suffix}",
                                 n_out)
                if "decode_ms" in out:      # disaggregated completions
                    # Their TTFT is router-measured (route start to
                    # prefill reply) — a different clock base than the
                    # replica-measured TTFT of unified completions, so
                    # it gets its own histogram instead of skewing
                    # ttft_ms percentiles in a mixed fleet.
                    self.metrics.observe("disagg_ttft_ms",
                                         out.get("ttft_ms"))
                    self.metrics.observe("decode_ms",
                                         out.get("decode_ms"))
                else:
                    self.metrics.observe("ttft_ms", out.get("ttft_ms"))
                self.metrics.observe("latency_ms", out.get("total_ms"))
                self.tracebook.finish(
                    tr, "completed", cls=cls,
                    tokens=len(out.get("tokens") or ()),
                    ttft_ms=out.get("ttft_ms"))
            else:
                self.metrics.inc("failed")
                if out.get("kind") == "deadline_exceeded":
                    # Router fail-fast or an in-batcher cancel: either
                    # way the deadline did its job — visible as its own
                    # counter, not buried in generic failures.
                    self.metrics.inc("deadline_exceeded")
                self.tracebook.finish(
                    tr, str(out.get("kind") or "error"), cls=cls)
            client.send(out)
