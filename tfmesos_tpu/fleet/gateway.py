"""The fleet's TCP front door.

The I/O plane is a :class:`~tfmesos_tpu.wire.WireServer` — ONE
selector-driven event loop carries every client connection (accept,
incremental Framer reads, buffered non-blocking writes), which is what
lifts the concurrent-connection ceiling from "one OS thread per client"
to "one fd per client" (docs/SERVING.md "Front-door scaling").  Request
EXECUTION keeps the worker-pool handoff: the loop-thread handler only
applies admission control (a shed costs one queue check, not a
dispatcher slot) and admitted requests wait in the bounded ingress
queue for one of ``workers`` dispatcher threads, which route them to a
replica and relay the completion back through the connection's
thread-safe buffered ``send``.

A fleet may run N gateways over ONE shared registry/router view
(``tfserve --gateways N``): each is stateless — any gateway can serve
any client — and registers its address for the ``gateways`` discovery
op, which clients use to find failover targets
(:class:`~tfmesos_tpu.fleet.client.FleetClient` replays idempotent
in-flight requests on a survivor when its gateway dies mid-stream).

Wire surface (all frames HMAC-authenticated with the cluster token):

* ``{"op": "generate", "id", "prompt", "max_new_tokens", "stop_token",
  "priority", "deadline_ms", "stream"}`` → ``{"op": "completion", "id",
  "tokens", "ttft_ms", "total_ms"}`` or ``{"op": "error", "id", "kind",
  "error"}`` with ``kind`` one of ``overloaded`` / ``rate_limited``
  (admission shed — back off), ``unavailable`` (no replica within the
  retry budget), ``bad_request``, ``deadline_exceeded`` (the request's
  end-to-end budget ran out — shed in the admission queue, failed fast
  by the router, or cancelled inside a replica's batcher; never
  retried).  ``deadline_ms`` (optional) is the request's END-TO-END
  budget in milliseconds from gateway receipt: the gateway stamps an
  absolute deadline, the WFQ queues shed expired work before dispatch,
  the router slices the remainder across its phases, and the replica's
  batcher cancels an expired resident row and frees its pages — no
  deadline preserves the flat ``request_timeout`` behavior exactly
  (docs/SERVING.md "Deadlines & failure containment").
  ``priority`` (optional; ``tenant`` is an alias) is
  the CLASS LABEL: it selects the weighted-fair admission queue the
  request waits in, and the class's preemption rank rides to the
  replica so a higher class can suspend lower-class resident rows under
  allocation pressure (docs/SERVING.md "Priorities, preemption &
  migration").  Unlabeled requests take the first-listed (default)
  class.
  ``stream`` (optional) asks for PER-TOKEN incremental replies: the
  completion's tokens are flushed as the replica's batcher emits them,
  as interleaved ``{"op": "tokens", "id", "off", "tokens"}`` frames
  (``off`` = tokens already streamed — the de-dup key across retries
  and failovers), followed by the usual final completion carrying the
  FULL list.  Old clients that never set it see exactly the old
  one-reply protocol.
  ``trace`` (optional) asks for FULL span detail on this request's
  trace: ``true`` under a gateway-minted id, a string to supply the
  trace id; every request gets an always-on summary trace regardless,
  and every reply (completion or error) carries its ``trace_id`` —
  fetch the waterfall later with the ``trace`` op (docs/SERVING.md
  "Observability").
* ``{"op": "metrics", "id"}`` → ``{"op": "metrics", "id", "snapshot"}``.
* ``{"op": "gateways", "id"}`` → ``{"op": "gateways", "id",
  "gateways": [addr, ...]}`` — the registered front doors of this
  fleet (client-side discovery for multi-gateway failover;
  ``tfserve gateways``).
* ``{"op": "trace", "id", "trace_id"? | "slowest": N? | "failed":
  true?, "limit"?}`` → ``{"op": "trace", "id", "traces": [...]}`` —
  one trace by id (full record), the N slowest, the newest failures,
  or the recent summaries (``tfserve trace``).
* ``{"op": "ping", "id"}`` → ``{"op": "pong", "id"}``.
* ``{"op": "rollout", "id", "weights_version"}`` → ``{"op": "rollout",
  "id", "ok": true, ...}`` or ``{"op": "error", "id", "kind":
  "rollout_failed" | "bad_request", "error"}`` — the blue-green weight
  rollout control op (``tfserve rollout``), served only when a fleet
  control plane is attached (``rollout_fn``); runs on its own thread
  and replies when the rollout completes or aborts.

Clients multiplex: many requests may be in flight per connection, and
completions return in FINISH order, matched by ``id`` — the same
streaming shape the replicas themselves speak.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import (AdmissionController,
                                         DeadlineExceeded, Overloaded,
                                         RateLimited)
from tfmesos_tpu.fleet.metrics import FleetMetrics
from tfmesos_tpu.fleet.router import Router
from tfmesos_tpu.fleet.tracing import TraceBook
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["Gateway", "RegistrySidecar"]


class Gateway:
    """Accepts streaming requests, admits, routes, relays completions."""

    def __init__(self, router: Router, admission: AdmissionController,
                 metrics: FleetMetrics, token: str = "",
                 host: str = "127.0.0.1", port: int = 0, workers: int = 8,
                 registry=None, tracebook: Optional[TraceBook] = None,
                 clock=time.monotonic, close_router: bool = True,
                 reuseport: bool = False,
                 http_port: Optional[int] = None,
                 http_host: Optional[str] = None):
        self.router = router
        self.admission = admission
        self.metrics = metrics
        # The deadline time base.  Injectable, and shared with the
        # router/admission clocks by the caller: the absolute deadline
        # stamped here is compared against the SAME clock at every
        # later checkpoint (WFQ shed, router loop head, timeout
        # slices) — stamping from a different clock than the checks
        # read would silently stretch or shrink every budget.
        self._clock = clock
        # Request tracing is on-by-default at SUMMARY level (every
        # request finishes into the book); span DETAIL is tail-retained
        # per the book's sample/slow/failure rules (docs/SERVING.md
        # "Observability").
        self.tracebook = tracebook if tracebook is not None else TraceBook()
        self.token = token
        self.host = host
        self.port = int(port)
        # SO_REUSEPORT (multi-process gateways sharing one public
        # port); the HTTP/SSE ingress listener (docs/SERVING.md
        # "HTTP/SSE edge") rides the same event loop when http_port is
        # set (0 = OS-assigned; see http_addr after start()).
        self.reuseport = bool(reuseport)
        self.http_port = http_port if http_port is None else int(http_port)
        self.http_host = http_host if http_host is not None else host
        self.http_addr: Optional[str] = None
        self.workers = int(workers)
        self.registry = registry if registry is not None else router.registry
        # N gateways share ONE router; only the last one standing may
        # close it.  False = the fleet launcher owns the router's
        # lifecycle (multi-gateway); True (default) keeps the
        # single-gateway teardown of old.
        self._close_router = bool(close_router)
        self.log = get_logger("tfmesos_tpu.fleet.gateway")
        self.addr: Optional[str] = None
        # The fleet control plane's rollout entry point (set by
        # FleetServer after bring-up): callable(version) -> info dict,
        # raising on abort.  None = this gateway has no rollout surface.
        self.rollout_fn = None
        # Model catalog (docs/SERVING.md "Model catalog"), both set by
        # FleetServer on catalog fleets: the catalog resolves/validates
        # the request's ``model`` label (absent -> the default entry;
        # unknown -> bad_request), and swap_adapter_fn is the adapter
        # hot-swap control plane (callable(model_id, version, meta,
        # body) -> info dict).  None = model-less fleet: a ``model``
        # label is charset-checked and forwarded as-is.
        self.catalog = None
        self.swap_adapter_fn = None
        self._server: Optional[wire.WireServer] = None
        self._stop = threading.Event()
        self._threads = []
        self.killed = False
        metrics.register_gauge("queue_depth", admission.depth)
        # Per-class depths: under a background flood the operator must
        # be able to see WHICH class is backed up (one global depth
        # reads as "overloaded" even while interactive sails through).
        metrics.register_gauge("queue_depths", admission.class_depths)
        metrics.register_gauge("replicas_alive",
                               lambda: len(self.registry.alive()))
        # Replicas registered but still compiling (--warmup): present
        # in the table, invisible to every router tier — surfaced so an
        # operator can tell "warming fleet" from "missing replicas".
        metrics.register_gauge("replicas_warming",
                               lambda: len(self.registry.warming()))
        # Per-role replica counts + aggregate outstanding/headroom, so
        # a disaggregated deployment's snapshot shows each tier served.
        metrics.register_gauge("roles", self.registry.role_summary)
        # Failure containment (docs/SERVING.md "Deadlines & failure
        # containment"): breaker state and the retry-budget level are
        # the on-call's first two questions during a brown-out, so they
        # ride the snapshot AND the periodic report line.
        metrics.register_gauge("breakers", router.breaker_summary)
        metrics.register_gauge("retry_budget", router.retry_budget_level)
        # Trace book occupancy + lifetime finish/detail counts — the
        # "is tracing actually retaining anything" sanity gauge.
        metrics.register_gauge("traces", self.tracebook.describe)
        # Fleet-wide KV-tier aggregate (summed per-replica heartbeat
        # counters: hits/misses/spills/promotions/park/resume + tier
        # occupancy) — the memory-hierarchy gauge, flattened into the
        # Prometheus exposition like every dict gauge.
        if hasattr(self.registry, "kv_tier_summary"):
            metrics.register_gauge("kv_tier",
                                   self.registry.kv_tier_summary)
        # Speculative decoding fleet-wide: replicas serving with a
        # draft and their aggregate acceptance rate — the biggest
        # single-stream latency lever's health number, visible through
        # `tfserve metrics` and Prometheus like every dict gauge.
        if hasattr(self.registry, "spec_summary"):
            metrics.register_gauge("spec", self.registry.spec_summary)
        # Per-model replica counts + adapter-version distribution (the
        # model catalog's membership gauge).
        if hasattr(self.registry, "model_summary"):
            metrics.register_gauge("models",
                                   self.registry.model_summary)
        # Gang replicas fleet-wide: gang count, member slots, joined
        # members, and degraded gangs (fewer joined than the mesh
        # needs) — flat numerics, so the Prometheus exposition carries
        # every field.
        if hasattr(self.registry, "gang_summary"):
            metrics.register_gauge("gangs", self.registry.gang_summary)
        # Items that expired while queued still owe the client an
        # explicit answer — the controller hands them back here from
        # whichever worker's get() swept them.
        admission.on_expired = self._queue_expired

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Gateway":
        self._server = wire.WireServer(
            self._handle, token=self.token, host=self.host,
            port=self.port, name="gateway", reuseport=self.reuseport,
            advertise_host=(None if self.host in ("0.0.0.0", "::")
                            else self.host))
        if self.http_port is not None:
            from tfmesos_tpu.fleet.http import HttpIngress

            self._server.add_ingress(HttpIngress(self),
                                     host=self.http_host,
                                     port=self.http_port)
        self._server.start()
        if self._server.ingress_addrs:
            self.http_addr = self._server.ingress_addrs[0]
            self.log.info("HTTP/SSE ingress on %s", self.http_addr)
        self.addr = self._server.addr
        self.log.info("fleet gateway listening on %s (%d workers, queue "
                      "bound %d, event-loop I/O)", self.addr,
                      self.workers, self.admission.max_queue)
        self._threads = []
        for i in range(self.workers):
            w = threading.Thread(target=self._worker, name=f"gateway-w{i}",
                                 daemon=True)
            w.start()
            self._threads.append(w)
        # Register this front door for client-side discovery (the
        # `gateways` op): stateless gateways over one registry view are
        # interchangeable, so any of them can hand out the full set.
        if hasattr(self.registry, "register_gateway"):
            self.registry.register_gateway(self.addr)
        return self

    def stop(self) -> None:
        """Graceful stop: deregister from discovery, close the event
        loop (clients see EOF), join the workers."""
        if hasattr(self.registry, "unregister_gateway") \
                and self.addr is not None:
            self.registry.unregister_gateway(self.addr)
        self._shutdown()
        if self._close_router:
            self.router.close()

    def kill(self) -> None:
        """Abrupt death (the bench's gateway 'SIGKILL'): connections
        slam shut mid-stream, nothing deregisters — exactly what peers
        of a SIGKILLed process observe.  The shared router/registry are
        untouched (they belong to the surviving gateways)."""
        self.killed = True
        self._shutdown()

    def _shutdown(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.stop()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []

    # -- ingress (runs on the event-loop thread: admit, never block) -------

    def _handle(self, client: "wire.WireConn", msg: Any) -> None:
        # Raw frames never reach here: the gateway's WireServer rejects
        # the raw bit at the length prefix (allow_raw defaults False),
        # which both keeps the public port's pre-auth buffering bound
        # at MAX_FRAME and fails a misdirected call_raw fast
        # (connection drop, never a timeout hang).
        if not isinstance(msg, dict):
            return
        op = msg.get("op")
        cid = msg.get("id")
        if op == "ping":
            client.send({"op": "pong", "id": cid})
            return
        if op == "metrics":
            out = {"op": "metrics", "id": cid,
                   "snapshot": self.metrics.snapshot()}
            if msg.get("raw"):
                # Mergeable state for the multi-process scrape fan-in:
                # histogram bucket vectors (not summaries), so a
                # fleet-level scraper can Histogram.merge() across N
                # gateway processes without losing percentiles.
                out["raw"] = self.metrics.raw_state()
            client.send(out)
            return
        if op == "gateways":
            reg = self.registry
            if hasattr(reg, "gateway_addrs"):
                addrs = reg.gateway_addrs()
            else:
                addrs = [self.addr] if self.addr else []
            client.send({"op": "gateways", "id": cid,
                         "gateways": addrs})
            return
        if op == "trace":
            # Authenticated read of the trace book: one trace by id,
            # the N slowest, the N newest failures, or the recent
            # summaries — the `tfserve trace` surface.
            book = self.tracebook
            limit = msg.get("limit")
            limit = int(limit) if isinstance(limit, (int, float)) \
                and not isinstance(limit, bool) and limit > 0 else 20
            tid = msg.get("trace_id")
            if isinstance(tid, str) and tid:
                rec = book.get(tid)
                traces = [rec] if rec is not None else []
            elif msg.get("failed"):
                traces = book.failed(limit)
            elif msg.get("slowest"):
                n = msg.get("slowest")
                traces = book.slowest(int(n) if isinstance(n, (int, float))
                                      and not isinstance(n, bool)
                                      and n > 0 else 5)
            else:
                traces = book.recent(limit)
            client.send({"op": "trace", "id": cid, "traces": traces})
            return
        if op == "rollout":
            fn = self.rollout_fn
            version = msg.get("weights_version")
            if fn is None:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "no rollout control plane attached "
                                      "to this gateway"})
                return
            if not isinstance(version, str) or not version:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "rollout needs a non-empty "
                                      "weights_version"})
                return

            def run_rollout() -> None:
                # Off the event-loop thread: a rollout takes as long as
                # a fleet's worth of warmups and drains, and blocking
                # here would stall EVERY connection, not just one.
                try:
                    info = fn(version)
                except Exception as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "rollout_failed",
                                 "error": str(e)})
                    return
                out = {"op": "rollout", "id": cid, "ok": True,
                       "weights_version": version}
                if isinstance(info, dict):
                    out.update(info)
                client.send(out)

            threading.Thread(target=run_rollout, name="gateway-rollout",
                             daemon=True).start()
            return
        if op == "swap_adapter":
            # Adapter hot-swap control op (docs/SERVING.md "Model
            # catalog").  The public port rejects raw frames at the
            # length prefix, so the delta arrives base64 in JSON and
            # the control plane re-ships it to the replicas as raw
            # HMAC frames.  Validation here is an INGRESS boundary:
            # model_id/adapter_version are charset-checked before they
            # touch anything.
            from tfmesos_tpu.fleet.catalog import decode_adapter_fields
            from tfmesos_tpu.fleet.registry import validate_model_id

            fn = self.swap_adapter_fn
            if fn is None:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": "no model catalog attached to "
                                      "this gateway"})
                return
            try:
                model_id = validate_model_id(msg.get("model_id"))
                version = validate_model_id(msg.get("adapter_version"))
                meta, body = decode_adapter_fields(msg.get("delta"))
            except (TypeError, ValueError) as e:
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request", "error": str(e)})
                return

            def run_swap() -> None:
                # Off the event-loop thread: the swap waits for every
                # replica's in-flight generations to finish on the old
                # delta, and blocking here would stall EVERY
                # connection.
                try:
                    info = fn(model_id, version, meta, body)
                except KeyError as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "bad_request",
                                 "error": str(e)})
                    return
                except Exception as e:
                    client.send({"op": "error", "id": cid,
                                 "kind": "swap_failed",
                                 "error": str(e)})
                    return
                out = {"op": "swap_adapter", "id": cid, "ok": True}
                if isinstance(info, dict):
                    out.update(info)
                client.send(out)

            threading.Thread(target=run_swap, name="gateway-swap",
                             daemon=True).start()
            return
        if op != "generate":
            client.send({"op": "error", "id": cid, "kind": "bad_request",
                         "error": f"unknown op {op!r}"})
            return
        self.metrics.inc("received")
        # Tracing begins at receipt: a client-supplied string is the
        # trace id (and asks for full detail), any other truthy value
        # asks for detail under a gateway-minted id, absence still gets
        # the always-on summary + tail-based retention.
        traw = msg.get("trace")
        tr = self.tracebook.begin(
            trace_id=traw if isinstance(traw, str) and traw else None,
            want_detail=bool(traw))
        # The class label ("priority"; "tenant" is an alias) picks the
        # weighted-fair admission queue; the class's preemption RANK —
        # not the label — rides to the replica, so batcher-side
        # preemption and gateway-side fair-share stay one coherent
        # policy defined in one place (the class table).
        label = msg.get("priority")
        if not isinstance(label, str):
            label = msg.get("tenant")
        spec = self.admission.resolve(
            label if isinstance(label, str) else None)
        # The model tier (docs/SERVING.md "Model catalog"): the label
        # is charset-validated at THIS ingress (it reaches Prometheus
        # metric names and the routing filter), resolved against the
        # catalog when one is attached — absent rides the default
        # entry, unknown is an explicit bad_request (there are no
        # weights to serve it, and billing it to the default would be
        # silently wrong).  Model-less fleets forward a validated
        # label as-is and route by exact replica match.
        from tfmesos_tpu.fleet.registry import MODEL_ID_RE

        mraw = msg.get("model")
        model = None
        if mraw is not None:
            if not (isinstance(mraw, str)
                    and MODEL_ID_RE.fullmatch(mraw)):
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "bad_request", cls=spec.name)
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request",
                             "error": f"invalid model label {mraw!r}",
                             "trace_id": tr.trace_id})
                return
            model = mraw
        if self.catalog is not None:
            try:
                model = self.catalog.resolve(model)
            except KeyError as e:
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "bad_request", cls=spec.name)
                client.send({"op": "error", "id": cid,
                             "kind": "bad_request", "error": str(e),
                             "trace_id": tr.trace_id})
                return
        prompt = msg.get("prompt")
        tr.event("gateway", "recv", cls=spec.name, rank=spec.rank,
                 model=model or "",
                 prompt_len=(len(prompt)
                             if isinstance(prompt, (list, tuple)) else 0))
        # End-to-end deadline: the client ships a RELATIVE budget
        # (clocks do not agree across hosts); the gateway stamps the
        # absolute expiry the whole serving path measures against.
        # A malformed or non-positive value costs the field, never the
        # request — no deadline is today's flat-timeout behavior.
        dl = msg.get("deadline_ms")
        deadline = None
        if isinstance(dl, (int, float)) and not isinstance(dl, bool) \
                and dl > 0:
            deadline = self._clock() + float(dl) / 1000.0
        forward = {"op": "generate", "prompt": msg.get("prompt"),
                   "max_new_tokens": msg.get("max_new_tokens"),
                   "stop_token": msg.get("stop_token"),
                   "priority": spec.rank,
                   # Internal (stripped before the wire, like
                   # "deadline"): the router records its attempts here
                   # and stitches replica hop spans back in.
                   "_trace": tr}
        if getattr(spec, "batch", False):
            # Internal routing hint: batch-lane work seeks IDLE
            # capacity, so the router prefers replicas with free
            # slots over the plain p2c draw (docs/SERVING.md
            # "Offline lane").
            forward["_background"] = True
        if msg.get("stream"):
            # Per-token streaming: the flag rides to the replica (whose
            # batcher flushes token frames per block) and the worker
            # installs the de-duplicating relay at dispatch.
            forward["stream"] = True
        sid = msg.get("session")
        if isinstance(sid, str) and sid:
            # Multi-turn session label (docs/SERVING.md "KV tiering &
            # sessions"): the router steers it at the replica holding
            # the parked KV, and the replica's batcher parks/resumes
            # under it.  Malformed values cost the field.
            forward["session"] = sid
        if model is not None:
            # Internal like "deadline"/"_trace": the router's model
            # tier filters on it (and re-stamps it onto the wire as
            # ``model`` for the replica's own cross-check).
            forward["_model"] = model
        if deadline is not None:
            forward["deadline"] = deadline
        try:
            self.admission.admit((client, cid, forward,
                                  time.perf_counter(), spec.name, tr),
                                 cls=spec.name, deadline=deadline,
                                 model=model)
        except DeadlineExceeded as e:
            self.metrics.inc("shed_deadline")
            self.metrics.inc(f"shed_deadline_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        except RateLimited as e:
            self.metrics.inc("shed_rate_limited")
            self.metrics.inc(f"shed_rate_limited_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        except Overloaded as e:
            self.metrics.inc("shed_queue")
            self.metrics.inc(f"shed_queue_{spec.name}")
            tr.event("admission", "shed", kind=e.kind, cls=spec.name)
            self.tracebook.finish(tr, e.kind, cls=spec.name)
            client.send({"op": "error", "id": cid, "kind": e.kind,
                         "error": str(e), "trace_id": tr.trace_id})
        else:
            self.metrics.inc("admitted")
            tr.event("admission", "enqueue", cls=spec.name)

    def handle_ingress(self, client, msg: Dict[str, Any]) -> None:
        """Submit one internal request on behalf of an ingress adapter
        (the HTTP/SSE edge): ``client`` is any object with a
        ``send(dict)`` (thread-safe) and a ``closed`` property — it
        rides the same admission/tracing/routing path as a wire
        connection, so the adapter inherits WFQ, deadlines, metering,
        and the exactly-once stream relay for free."""
        self.metrics.inc("http_requests")
        self._handle(client, msg)

    def _queue_expired(self, item) -> None:
        """One admitted request expired while waiting in its class
        queue (AdmissionController.get shed it before dispatch): the
        client still gets its explicit answer, and the books stay
        consistent — it was admitted, so it counts as failed too."""
        client, cid, _forward, t_enq, cls, tr = item
        self.metrics.inc("shed_deadline")
        self.metrics.inc(f"shed_deadline_{cls}")
        self.metrics.inc("failed")
        tr.add("admission", "queue_wait", tr.rel_ms(t_enq),
               (time.perf_counter() - t_enq) * 1000.0, cls=cls,
               expired=True)
        self.tracebook.finish(tr, "deadline_exceeded", cls=cls,
                              where="queued")
        client.send({"op": "error", "id": cid,
                     "kind": "deadline_exceeded",
                     "error": "request deadline expired while queued "
                              "at the gateway",
                     "trace_id": tr.trace_id})

    # -- dispatch ----------------------------------------------------------

    def _stream_relay(self, client: "wire.WireConn", cid):
        """The per-request partial-frame relay: forwards each replica
        ``tokens`` frame to the client re-keyed to ITS request id,
        de-duplicated by stream offset — a retried/resumed attempt
        re-streams from 0 (deterministic completions), and only tokens
        past the high-water mark go out, each exactly once."""
        sent = [0]

        def emit(frame) -> None:
            if isinstance(frame, wire.RawFrame):
                return              # never a token frame
            toks = frame.get("tokens")
            if not isinstance(toks, list) or not toks:
                return
            off = frame.get("off")
            off = int(off) if isinstance(off, (int, float)) \
                and not isinstance(off, bool) else 0
            prev = sent[0]
            new = toks[max(0, prev - off):]
            if not new or off + len(toks) <= prev:
                return
            sent[0] = off + len(toks)
            self.metrics.inc("stream_chunks")
            client.send({"op": "tokens", "id": cid,
                         "off": prev, "tokens": new})

        # Disconnect probe (docs/SERVING.md "HTTP/SSE edge"): the
        # router polls this per relayed frame and, once the client is
        # gone, cancels the replica-side row with a one-way ``cancel``
        # op — a walked-away SSE client (or a dropped wire conn) frees
        # its pages within a decode tick instead of decoding to the
        # bitter end.
        emit.cancelled = lambda: bool(getattr(client, "closed", False))
        return emit

    def _worker(self) -> None:
        while not self._stop.is_set():
            item = self.admission.get(timeout=0.2)
            if item is None:
                continue
            client, cid, forward, t_enq, cls, tr = item
            # Queue wait is ITS OWN histogram, never folded into TTFT:
            # TTFT measures the serving path (prefill + transfer), and
            # conflating admission backlog with it would mask exactly
            # the stalls disaggregation removes.  The per-class variant
            # is what the priority bench (and an SLO dashboard) reads —
            # the global one stays the autoscaler's signal.
            wait_ms = (time.perf_counter() - t_enq) * 1000.0
            self.metrics.observe("queue_wait_ms", wait_ms)
            self.metrics.observe(f"queue_wait_ms_{cls}", wait_ms)
            model = forward.get("_model")
            if model:
                # The per-MODEL queue-wait histogram is the model
                # trader's relative-pressure signal (windowed p99 per
                # model is what decides who trades replicas to whom).
                self.metrics.observe(f"queue_wait_ms_model_{model}",
                                     wait_ms)
            # The WFQ dequeue closes the queue-wait span — the first
            # hop of every waterfall.
            tr.add("admission", "queue_wait", tr.rel_ms(t_enq), wait_ms,
                   cls=cls)
            if forward.get("stream"):
                forward["_emit"] = self._stream_relay(client, cid)
            try:
                reply = self.router.route(forward)
            except Exception as e:
                # Any routing failure (RoutingError or unexpected)
                # becomes an explicit client error; a gateway worker
                # must survive everything.
                self.metrics.inc("failed")
                self.tracebook.finish(tr, "unavailable", cls=cls,
                                      error=str(e)[:200])
                client.send({"op": "error", "id": cid,
                             "kind": "unavailable", "error": str(e),
                             "trace_id": tr.trace_id})
                continue
            out = dict(reply) if isinstance(reply, dict) else {
                "op": "error", "kind": "internal",
                "error": f"malformed replica reply {reply!r}"}
            out["id"] = cid
            out["trace_id"] = tr.trace_id
            # Belt-and-braces: the router absorbs piggybacked replica
            # spans into the trace and pops them, but a reply that
            # bypassed absorption must not leak span payloads to the
            # client.
            out.pop("trace", None)
            if out.get("op") == "completion":
                self.metrics.inc("completed")
                n_out = len(out.get("tokens") or ())
                self.metrics.inc("tokens_out", n_out)
                # Billing-grade metering: prompt and decode tokens per
                # tenant-class x model (docs/SERVING.md "Model
                # catalog").  Plain counters, so they ride the
                # snapshot AND the Prometheus exposition (names
                # sanitized there); counted only on DELIVERED
                # completions — failed work is not billable.
                suffix = f"{cls}_{model}" if model else cls
                self.metrics.inc(f"metering_prompt_tokens_{suffix}",
                                 len(forward.get("prompt") or ()))
                self.metrics.inc(f"metering_decode_tokens_{suffix}",
                                 n_out)
                if "decode_ms" in out:      # disaggregated completions
                    # Their TTFT is router-measured (route start to
                    # prefill reply) — a different clock base than the
                    # replica-measured TTFT of unified completions, so
                    # it gets its own histogram instead of skewing
                    # ttft_ms percentiles in a mixed fleet.
                    self.metrics.observe("disagg_ttft_ms",
                                         out.get("ttft_ms"))
                    self.metrics.observe("decode_ms",
                                         out.get("decode_ms"))
                else:
                    self.metrics.observe("ttft_ms", out.get("ttft_ms"))
                self.metrics.observe("latency_ms", out.get("total_ms"))
                self.tracebook.finish(
                    tr, "completed", cls=cls,
                    tokens=len(out.get("tokens") or ()),
                    ttft_ms=out.get("ttft_ms"))
            else:
                self.metrics.inc("failed")
                if out.get("kind") == "deadline_exceeded":
                    # Router fail-fast or an in-batcher cancel: either
                    # way the deadline did its job — visible as its own
                    # counter, not buried in generic failures.
                    self.metrics.inc("deadline_exceeded")
                self.tracebook.finish(
                    tr, str(out.get("kind") or "error"), cls=cls)
            client.send(out)


# -- multi-process gateways --------------------------------------------------


class RegistrySidecar:
    """A gateway PROCESS's registry client (docs/SERVING.md
    "Multi-process gateways"): polls the central registry's
    ``registry_view`` op over one persistent wire connection and
    replays the table into a process-LOCAL
    :class:`~tfmesos_tpu.fleet.registry.ReplicaRegistry` — constructed
    but never ``start()``ed (no listener socket, no sweeper thread) —
    which this process's router and admission WFQ read exactly as the
    in-process launcher path would.  No shared-memory hacks: the
    sidecar rides the same heartbeat/wire surface replicas use, so N
    gateway processes scale like N more wire peers.

    Per poll it also re-LEASES this gateway's own address into central
    discovery (``register_gateway`` with a TTL), so a SIGKILLed
    process expires out of the ``gateways`` op on its own, and syncs
    the central discovery set back into the local registry so any
    gateway process answers discovery with the full fleet set.

    State translation per replayed entry: ALIVE/WARMING arrive as
    plain heartbeats (``status: warming`` preserved), DRAINING as a
    heartbeat plus a ``drain`` op, DEAD as :meth:`mark_dead`.  The
    local sweeper (called inline per poll) ages out whatever the
    central view stops listing — and if the central registry itself
    becomes unreachable, the local table goes stale and drains on its
    own clocks: fail-safe, never fail-frozen."""

    def __init__(self, registry_addr: str, token: str = "",
                 poll_interval: float = 0.25, metrics=None,
                 clock=time.monotonic):
        from tfmesos_tpu.fleet.registry import ReplicaRegistry

        self.registry_addr = registry_addr
        self.token = token
        self.poll_interval = float(poll_interval)
        self.lease_ttl = min(30.0, max(2.0, 8.0 * self.poll_interval))
        self._clock = clock
        # Liveness thresholds scale with the poll cadence the same way
        # the central registry's scale with the heartbeat interval: a
        # slow poll must not flap mirrored entries between refreshes.
        self.local = ReplicaRegistry(
            token=token, metrics=metrics, clock=clock,
            suspect_after=max(1.5, 6.0 * self.poll_interval),
            dead_after=max(3.0, 12.0 * self.poll_interval),
            evict_after=max(10.0, 24.0 * self.poll_interval))
        # The address this process leases into discovery; set by main()
        # once its Gateway has bound.  scrape_addr is the PRIVATE
        # per-process listener (metrics fan-in + lease identity under
        # a shared REUSEPORT public addr).
        self.gateway_addr: Optional[str] = None
        self.scrape_addr: Optional[str] = None
        self.polls = 0
        self.poll_failures = 0
        self.log = get_logger("tfmesos_tpu.fleet.gateway")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if metrics is not None:
            metrics.register_gauge("sidecar_polls", lambda: self.polls)
            metrics.register_gauge("sidecar_poll_failures",
                                   lambda: self.poll_failures)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RegistrySidecar":
        self._thread = threading.Thread(target=self._loop,
                                        name="gateway-sidecar",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def wait_for_replicas(self, n: int, timeout: float = 60.0) -> bool:
        """Block until the LOCAL view mirrors >= n alive replicas."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.local.alive()) >= n:
                return True
            if self._stop.wait(0.05):
                return False
        return len(self.local.alive()) >= n

    # -- the poll loop ------------------------------------------------------

    def _loop(self) -> None:
        sock = None
        it = None
        logged_down = False
        while not self._stop.is_set():
            try:
                if sock is None:
                    sock = wire.connect(self.registry_addr, timeout=5.0)
                    framer = wire.Framer(self.token)
                    it = wire.iter_msgs(sock, framer)
                if self.gateway_addr:
                    lease = {"op": "register_gateway",
                             "addr": self.gateway_addr,
                             "ttl": self.lease_ttl}
                    if self.scrape_addr:
                        lease["scrape"] = self.scrape_addr
                    wire.send_msg(sock, lease, self.token)
                    next(it)            # gateway_registered ack
                wire.send_msg(sock, {"op": "registry_view"}, self.token)
                self._apply(next(it))
                self.polls += 1
                logged_down = False
            except (OSError, wire.WireError, StopIteration) as e:
                self.poll_failures += 1
                if not logged_down:
                    logged_down = True
                    self.log.warning(
                        "registry poll to %s failed (%s); local view "
                        "will age out until it recovers",
                        self.registry_addr, e)
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                sock = it = None
            # Liveness over the MIRROR: entries the central view stops
            # listing (evicted there) stop being refreshed here and age
            # out through the standard sweep ladder.
            self.local.sweep()
            self._stop.wait(self.poll_interval)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _apply(self, view: Any) -> None:
        if not isinstance(view, dict) \
                or view.get("op") != "registry_view":
            return
        from tfmesos_tpu.fleet import registry as registry_mod

        for d in view.get("replicas") or []:
            if not isinstance(d, dict) or not d.get("addr"):
                continue
            state = d.get("state")
            if state == registry_mod.DEAD:
                self.local.mark_dead(d["addr"],
                                     why="dead in central registry view")
                continue
            beat = {k: v for k, v in d.items() if k != "state"}
            self.local.observe(beat)
            if state == registry_mod.DRAINING:
                self.local.observe({"op": "drain", "addr": d["addr"]})
        gws = view.get("gateways")
        if isinstance(gws, list):
            self.local.set_gateways([a for a in gws
                                     if isinstance(a, str)])


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tfmesos_tpu.fleet.gateway",
        description="One fleet gateway PROCESS: Gateway + admission "
                    "WFQ + router over a registry-view sidecar — the "
                    "multi-process front door (jax-free).")
    p.add_argument("--registry", type=str, required=True,
                   help="central registry host:port (the same address "
                        "replicas heartbeat)")
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="wire listen port (0 = OS-assigned); with "
                        "--reuseport every gateway process passes the "
                        "SAME port and the kernel load-balances "
                        "accepts across them")
    p.add_argument("--reuseport", action="store_true",
                   help="bind with SO_REUSEPORT (multi-process "
                        "gateways sharing one public port; fails "
                        "explicitly where unsupported)")
    p.add_argument("--http-port", type=int, default=None,
                   dest="http_port",
                   help="serve the HTTP/1.1+SSE ingress adapter on "
                        "this port (0 = OS-assigned; default: no HTTP "
                        "listener)")
    p.add_argument("--http-host", type=str, default=None,
                   dest="http_host")
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--max-queue", type=int, default=None,
                   dest="max_queue")
    p.add_argument("--rate", type=float, default=None,
                   help="token-bucket admission rate (req/s)")
    p.add_argument("--burst", type=float, default=None)
    p.add_argument("--max-retries", type=int, default=2,
                   dest="max_retries")
    p.add_argument("--request-timeout", type=float, default=120.0,
                   dest="request_timeout")
    p.add_argument("--poll-interval", type=float, default=0.25,
                   dest="poll_interval",
                   help="registry-view sidecar poll cadence in seconds")
    p.add_argument("--metrics-port", type=int, default=None,
                   dest="metrics_port",
                   help="per-process Prometheus exposition port (falls "
                        "back to an OS-assigned port when taken; see "
                        "the metrics_http_port gauge)")
    return p


def main(argv=None) -> int:
    import signal

    args = build_parser().parse_args(argv)
    token = wire.load_token()
    metrics = FleetMetrics()
    sidecar = RegistrySidecar(args.registry, token=token,
                              poll_interval=args.poll_interval,
                              metrics=metrics)
    router = Router(sidecar.local, metrics, token=token,
                    max_retries=args.max_retries,
                    request_timeout=args.request_timeout)
    adm_kwargs: Dict[str, Any] = {}
    if args.max_queue is not None:
        adm_kwargs["max_queue"] = args.max_queue
    admission = AdmissionController(rate=args.rate, burst=args.burst,
                                    **adm_kwargs)
    gw = Gateway(router, admission, metrics, token=token,
                 host=args.host, port=args.port, workers=args.workers,
                 registry=sidecar.local, reuseport=args.reuseport,
                 http_port=args.http_port,
                 http_host=args.http_host).start()

    # Private per-process listener: with SO_REUSEPORT a dial to the
    # shared public addr lands on a KERNEL-chosen process, so the
    # launcher's metrics fan-in (and the lease identity that keeps N
    # same-addr processes distinct in discovery) needs an address that
    # reaches THIS process deterministically.
    def on_scrape(conn, msg) -> None:
        op = msg.get("op") if isinstance(msg, dict) else None
        mid = msg.get("id") if isinstance(msg, dict) else None
        if op == "metrics":
            out: Dict[str, Any] = {"op": "metrics", "id": mid,
                                   "metrics": metrics.snapshot()}
            if msg.get("raw"):
                out["raw"] = metrics.raw_state()
            conn.send(out)
        elif op == "ping":
            conn.send({"op": "pong", "id": mid})
        elif op == "status":
            # Mirror-convergence probe: how much of the fleet THIS
            # process's sidecar view can already route to.  The
            # launcher polls this at bring-up so a client's first
            # request never lands on a gateway that mirrors nothing.
            conn.send({"op": "status", "id": mid,
                       "alive": len(sidecar.local.alive()),
                       "polls": sidecar.polls})
        else:
            conn.send({"op": "error", "id": mid,
                       "error": {"kind": "bad_request",
                                 "message": "scrape listener serves "
                                            "metrics/ping/status "
                                            "only"}})

    scrape_srv = wire.WireServer(on_scrape, token=token, host=args.host,
                                 port=0, name="gateway-scrape").start()
    sidecar.gateway_addr = gw.addr
    sidecar.scrape_addr = scrape_srv.addr
    sidecar.start()
    if args.metrics_port is not None:
        metrics.start_http_server(args.metrics_port)
    line = f"gateway serving on {gw.addr}"
    if gw.http_addr:
        line += f" (http {gw.http_addr})"
    print(line, flush=True)
    stop = threading.Event()

    def on_signal(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    stop.wait()
    sidecar.stop()
    scrape_srv.stop()
    gw.stop()
    return 0


if __name__ == "__main__":       # pragma: no cover - process entry
    import sys

    sys.exit(main())
