"""Admission control and backpressure for the fleet gateway.

Overload policy, in order of application:

1. **Token-bucket rate limiter** (optional): a sustained requests/s cap
   with a burst allowance.  Over-rate arrivals are rejected with
   :class:`RateLimited` before they cost anything downstream.
2. **Bounded ingress queue**: accepted requests wait here for a
   dispatcher; when the queue is full the arrival is rejected with
   :class:`Overloaded`.

Both rejections are EXPLICIT wire replies — the contract is "never a
hang": a client always gets either a completion or an immediate
overload signal it can back off on.  (The alternative — unbounded
queueing — converts overload into unbounded latency, which at serving
scale is indistinguishable from an outage.)
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

__all__ = ["Overloaded", "RateLimited", "TokenBucket",
           "AdmissionController"]


class Overloaded(Exception):
    """Explicit shed: the ingress queue is at its bound."""

    kind = "overloaded"


class RateLimited(Overloaded):
    """Explicit shed: the token bucket is empty."""

    kind = "rate_limited"


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity; ``try_acquire`` never blocks (admission sheds instead of
    queueing over-rate work)."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class AdmissionController:
    """Bounded ingress queue + optional rate limiter.

    The gateway's connection threads call :meth:`admit` (which raises
    on shed); its dispatcher workers call :meth:`get`.  ``depth()`` is
    exported as the ``queue_depth`` gauge.
    """

    def __init__(self, max_queue: int = 64, rate: Optional[float] = None,
                 burst: Optional[float] = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.bucket = TokenBucket(rate, burst) if rate is not None else None
        self._q: "queue.Queue[Any]" = queue.Queue(maxsize=self.max_queue)

    def admit(self, item: Any) -> None:
        """Enqueue ``item`` or raise — never blocks the caller's
        connection thread."""
        if self.bucket is not None and not self.bucket.try_acquire():
            raise RateLimited(
                f"rate limit exceeded ({self.bucket.rate:g} req/s, "
                f"burst {self.bucket.burst:g})")
        try:
            self._q.put_nowait(item)
        except queue.Full:
            raise Overloaded(
                f"ingress queue full ({self.max_queue} requests "
                f"waiting)") from None

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next admitted item, or ``None`` on timeout (workers poll so
        shutdown never needs queue poisoning)."""
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def depth(self) -> int:
        return self._q.qsize()
