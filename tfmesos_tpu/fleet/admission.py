"""Admission control and backpressure for the fleet gateway.

Overload policy, in order of application:

1. **Deadline sheds**: a request whose end-to-end deadline has already
   passed is rejected with :class:`DeadlineExceeded` BEFORE the
   capacity check and BEFORE the token bucket is consulted — expired
   work must cost the fleet nothing, not a queue slot and not a rate
   token (the client gave up; serving it would be pure waste).  The
   same check runs again at DISPATCH (:meth:`AdmissionController.get`):
   a request that expired while waiting in its class queue is shed
   there with the per-class ``shed_deadline`` counter and handed to the
   ``on_expired`` callback so the gateway can still answer the client
   explicitly.
2. **Per-class bounded queues**: every request belongs to a priority
   class (the ``priority``/``tenant`` label on the wire, mapped here);
   each class has its own queue bound, and a full class sheds with
   :class:`Overloaded` WITHOUT touching any other class's capacity — a
   background flood fills the background queue and sheds there, while
   interactive arrivals keep being admitted.
3. **Token-bucket rate limiter** (optional): a sustained requests/s cap
   with a burst allowance, checked only AFTER the queue-capacity check
   so a shed never burns a token (an overloaded gateway must not
   double-penalize clients).  Over-rate arrivals are rejected with
   :class:`RateLimited`.

Dispatch is **weighted fair queueing** across the classes: each
admitted item gets a virtual-time finish tag ``max(vnow, class_last) +
1/weight`` and :meth:`AdmissionController.get` always serves the
smallest tag — so a class with weight ``w`` is guaranteed ~``w/Σw`` of
dispatcher throughput whenever it has work, and no class can starve
another no matter how hard it floods (the flood's tags race ahead of
the victim's).  With one class (the default) this degenerates to the
original FIFO queue exactly.

Both rejections are EXPLICIT wire replies — the contract is "never a
hang": a client always gets either a completion or an immediate
overload signal it can back off on.  (The alternative — unbounded
queueing — converts overload into unbounded latency, which at serving
scale is indistinguishable from an outage.)
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Overloaded", "RateLimited", "DeadlineExceeded", "TokenBucket",
           "PriorityClass", "AdmissionController", "DEFAULT_MAX_QUEUE"]

#: Default per-class ingress queue bound.  64 holds ~8 dispatch rounds
#: of backlog for the default 8-worker gateway before shedding; the
#: simulator's ``steady`` scenario (``tfserve simulate steady --sweep
#: admission.max_queue=16,64,256``) shows queue-wait p99 growing
#: roughly linearly with the bound under overload while the shed rate
#: falls — 64 keeps p99 under one service time at 2x overload.
#: Sweepable by path as ``admission.max_queue``.
DEFAULT_MAX_QUEUE = 64


class Overloaded(Exception):
    """Explicit shed: the ingress queue is at its bound."""

    kind = "overloaded"


class RateLimited(Overloaded):
    """Explicit shed: the token bucket is empty."""

    kind = "rate_limited"


class DeadlineExceeded(Exception):
    """Explicit shed: the request's end-to-end deadline already passed.
    Deliberately NOT an :class:`Overloaded` — the fleet is not asking
    the client to back off, it is telling it this request is dead."""

    kind = "deadline_exceeded"


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill up to ``burst``
    capacity; ``try_acquire`` never blocks (admission sheds instead of
    queueing over-rate work)."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(1.0, rate))
        if self.burst < 1.0:
            raise ValueError(f"burst must be >= 1, got {self.burst}")
        self._clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


@dataclasses.dataclass
class PriorityClass:
    """One admission class (docs/SERVING.md "Priorities, preemption &
    migration").

    ``weight`` is the WFQ share (a weight-8 class gets ~8x the
    dispatcher throughput of a weight-1 class under contention);
    ``rank`` is the PREEMPTION priority forwarded to replicas (higher
    rank may suspend lower-rank resident rows under allocation
    pressure) — the two are deliberately separate knobs: fair-share is
    about throughput under sustained load, preemption about latency of
    the next arrival.  ``max_queue`` bounds this class's own ingress
    queue (``None`` = the controller default).  ``model_quota`` bounds
    how many of the queued slots ONE model may hold within this class
    (the per-tenant+per-model quota of the model catalog,
    docs/SERVING.md "Model catalog"): a tenant flooding one model
    sheds there without starving its own traffic to other models;
    ``None`` = unlimited, the pre-catalog behavior exactly.

    ``batch`` marks the OFFLINE lane (docs/SERVING.md "Offline lane"):
    a batch class dispatches only when every non-batch queue is EMPTY —
    strict background priority BELOW the WFQ fair-share, so batch work
    soaks up idle dispatcher capacity without ever consuming a share an
    interactive class could have used.  Batch classes are deadline-less
    by convention (submitters omit ``deadline_ms``) and should carry a
    ``rank`` below every interactive class so resident batch rows yield
    their decode slots to the first interactive arrival via the
    replica's preemption machinery."""

    name: str
    weight: float = 1.0
    rank: int = 0
    max_queue: Optional[int] = None
    model_quota: Optional[int] = None
    batch: bool = False

    def __post_init__(self):
        if not self.name:
            raise ValueError("priority class needs a non-empty name")
        if self.model_quota is not None and self.model_quota < 1:
            raise ValueError(f"class {self.name!r} model_quota must be "
                             f">= 1, got {self.model_quota}")
        # Finite AND positive: a NaN weight poisons every WFQ tag
        # comparison (dispatch order degrades to dict order) and an
        # inf weight's zero tag increment would starve every other
        # class — both break the no-starvation guarantee silently.
        if not math.isfinite(self.weight) or self.weight <= 0:
            raise ValueError(f"class {self.name!r} weight must be a "
                             f"finite positive number, got {self.weight}")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(f"class {self.name!r} max_queue must be "
                             f">= 1, got {self.max_queue}")


class _ClassQ:
    """One class's live state: spec + queue + WFQ tag + shed counters."""

    __slots__ = ("spec", "q", "last_tag", "shed_queue", "shed_rate",
                 "shed_deadline", "shed_quota", "admitted",
                 "model_counts")

    def __init__(self, spec: PriorityClass):
        self.spec = spec
        # (finish_tag, seq, item, deadline, model)
        self.q: deque = deque()
        self.last_tag = 0.0
        self.shed_queue = 0
        self.shed_rate = 0
        self.shed_deadline = 0
        self.shed_quota = 0
        self.admitted = 0
        # model -> queued count (the per-tenant+per-model quota's
        # live book; decremented as items dequeue or expire).
        self.model_counts: Dict[str, int] = {}

    def _model_out(self, model: Optional[str]) -> None:
        if model is None:
            return
        n = self.model_counts.get(model, 0) - 1
        if n > 0:
            self.model_counts[model] = n
        else:
            self.model_counts.pop(model, None)


class AdmissionController:
    """Per-class bounded queues + WFQ dispatch + optional rate limiter.

    The gateway's connection threads call :meth:`admit` (which raises
    on shed); its dispatcher workers call :meth:`get`.  ``depth()`` is
    exported as the ``queue_depth`` gauge, :meth:`class_depths` as the
    per-class one.  Without ``classes`` this is exactly the original
    single-FIFO controller.
    """

    def __init__(self, max_queue: int = DEFAULT_MAX_QUEUE,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 classes: Optional[List[PriorityClass]] = None,
                 clock=time.monotonic):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)
        self.bucket = TokenBucket(rate, burst, clock=clock) \
            if rate is not None else None
        specs = list(classes) if classes else [PriorityClass("default")]
        names = [c.name for c in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate priority class names in {names}")
        self._classes: Dict[str, _ClassQ] = {
            c.name: _ClassQ(c) for c in specs}
        # Unlabeled (and unknown-label) traffic maps to the FIRST
        # listed class — operators list highest-priority first, so
        # adding a background tier never degrades existing clients.
        self._default = specs[0].name
        self._clock = clock
        self._cond = threading.Condition()
        self._vtime = 0.0           # virtual time = last dispatched tag
        self._seq = 0               # FIFO tiebreak within equal tags
        # Called (outside the lock) with each item shed at DISPATCH
        # time because its deadline passed while queued — the gateway
        # hooks this to send the client its explicit
        # ``deadline_exceeded`` error instead of a silent drop.
        self.on_expired: Optional[Any] = None

    # -- class resolution --------------------------------------------------

    def resolve(self, label: Optional[str]) -> PriorityClass:
        """The class a request labeled ``label`` belongs to (the
        default class for ``None`` or an unknown label — a typo'd
        tenant must be served, just without special treatment)."""
        c = self._classes.get(label) if isinstance(label, str) else None
        if c is None:
            c = self._classes[self._default]
        return c.spec

    @property
    def class_names(self) -> List[str]:
        return list(self._classes)

    # -- admission ---------------------------------------------------------

    def admit(self, item: Any, cls: Optional[str] = None,
              deadline: Optional[float] = None,
              model: Optional[str] = None) -> None:
        """Enqueue ``item`` under class ``cls`` or raise — never blocks
        the caller's connection thread.  ``deadline`` is an absolute
        clock reading (the controller's ``clock``, monotonic by
        default) past which the request is dead: an already-expired
        arrival sheds FIRST — before the capacity check and before the
        token bucket, which must not be debited for work nobody will
        wait for — and a queued item that expires before dispatch is
        shed by :meth:`get`.  Capacity is checked BEFORE the token
        bucket is debited: a shed must not also burn a token
        (double-penalizing clients exactly when the gateway is already
        overloaded)."""
        spec = self.resolve(cls)
        c = self._classes[spec.name]
        bound = spec.max_queue if spec.max_queue is not None \
            else self.max_queue
        with self._cond:
            if deadline is not None and self._clock() >= deadline:
                c.shed_deadline += 1
                raise DeadlineExceeded(
                    f"request deadline expired before admission "
                    f"(class {spec.name!r})")
            if len(c.q) >= bound:
                c.shed_queue += 1
                raise Overloaded(
                    f"ingress queue full for class {spec.name!r} "
                    f"({bound} requests waiting)")
            if model is not None and spec.model_quota is not None \
                    and c.model_counts.get(model, 0) >= spec.model_quota:
                # Per-tenant+per-model quota (checked AFTER the class
                # bound — one consistent shed order — and BEFORE the
                # token bucket, which a shed must never debit): this
                # tenant's flood of ONE model sheds without touching
                # its own slots for other models or any other class.
                c.shed_quota += 1
                raise Overloaded(
                    f"model quota full for class {spec.name!r} / model "
                    f"{model!r} ({spec.model_quota} queued)")
            if self.bucket is not None and not self.bucket.try_acquire():
                c.shed_rate += 1
                raise RateLimited(
                    f"rate limit exceeded ({self.bucket.rate:g} req/s, "
                    f"burst {self.bucket.burst:g})")
            # WFQ virtual-time finish tag: service owed to this class so
            # far (its last tag) or global virtual now, whichever is
            # later, plus this item's 1/weight of service.
            tag = max(self._vtime, c.last_tag) + 1.0 / spec.weight
            c.last_tag = tag
            self._seq += 1
            c.q.append((tag, self._seq, item, deadline, model))
            if model is not None:
                c.model_counts[model] = c.model_counts.get(model, 0) + 1
            c.admitted += 1
            self._cond.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Next admitted item in WFQ order (smallest finish tag wins;
        FIFO within a class), or ``None`` on timeout — workers poll so
        shutdown never needs queue poisoning.  Items whose deadline
        passed while queued are shed here, BEFORE dispatch: each counts
        its class's ``shed_deadline`` and is handed to ``on_expired``
        (outside the lock), and the walk continues to the next live
        item — expired work never reaches a router worker."""
        item, expired = self._get(timeout)
        cb = self.on_expired
        if cb is not None:
            for it in expired:
                try:
                    cb(it)
                except Exception:   # pragma: no cover - gateway's duty
                    pass
        return item

    def _get(self, timeout: Optional[float]) -> tuple:
        # The poll deadline runs on the INJECTED clock, like the
        # deadline sheds above — under time.monotonic (production) this
        # is the old behavior exactly; under the simulator's virtual
        # clock a timeout=0 poll returns without ever touching the
        # condition's real-time wait (calling time.monotonic here
        # directly was a latent clock-mixing bug for any injected-clock
        # caller).
        poll_deadline = None if timeout is None \
            else self._clock() + timeout
        expired = []
        with self._cond:
            while True:
                # WFQ over the non-batch classes first; the offline
                # lane (batch=True classes) is served ONLY when every
                # non-batch queue is empty — strict background
                # priority, so batch backlog can never dilute an
                # interactive class's fair share.
                best = None
                for c in self._classes.values():
                    if c.spec.batch or not c.q:
                        continue
                    if best is None or c.q[0][:2] < best.q[0][:2]:
                        best = c
                if best is None:
                    for c in self._classes.values():
                        if not c.spec.batch or not c.q:
                            continue
                        if best is None or c.q[0][:2] < best.q[0][:2]:
                            best = c
                if best is not None:
                    tag, _, item, dl, model = best.q.popleft()
                    best._model_out(model)
                    if tag > self._vtime:
                        self._vtime = tag
                    if dl is not None and self._clock() >= dl:
                        best.shed_deadline += 1
                        expired.append(item)
                        continue
                    return item, expired
                remaining = None if poll_deadline is None \
                    else poll_deadline - self._clock()
                if remaining is not None and remaining <= 0:
                    return None, expired
                if not self._cond.wait(remaining):
                    return None, expired

    # -- observability -----------------------------------------------------

    def depth(self) -> int:
        with self._cond:
            return sum(len(c.q) for c in self._classes.values())

    def class_depths(self) -> Dict[str, int]:
        """Per-class queue depths (the gateway's ``queue_depths``
        gauge)."""
        with self._cond:
            return {name: len(c.q) for name, c in self._classes.items()}

    def shed_counts(self) -> Dict[str, Tuple[int, int, int]]:
        """Per-class ``(queue sheds, rate sheds, deadline sheds)``
        since start."""
        with self._cond:
            return {name: (c.shed_queue, c.shed_rate, c.shed_deadline)
                    for name, c in self._classes.items()}

    def quota_shed_counts(self) -> Dict[str, int]:
        """Per-class sheds from the per-tenant+per-model quota."""
        with self._cond:
            return {name: c.shed_quota
                    for name, c in self._classes.items()}
