"""End-to-end request tracing for the serving fleet.

Every generate request gets a ``trace_id`` minted at the gateway (or
supplied by the client) that rides the wire alongside ``deadline_ms``;
each component appends :class:`Span` records as the request moves —
gateway receipt, class resolution, WFQ queue wait, every router attempt
with its outcome taxonomy (picked replica, breaker skips, retry cause,
budget debits, deadline clips), prefill/decode phases, batcher-level
events (admission, preemption, suspend/export, import/resume, deadline
cancel), and migration hops.  Three disciplines keep it cheap and
correct at fleet scale:

* **Hop-local clocks.**  Absolute clock readings never cross the wire
  (the same rule end-to-end deadlines follow): a replica's spans are
  offsets from ITS OWN receipt of the request, piggybacked on the reply
  as plain dicts, and the router re-anchors them at the attempt's start
  on the gateway's clock (:meth:`TraceContext.absorb`).  The stitched
  waterfall is therefore exact within a hop and network-shifted across
  hops — durations are always true.
* **Tail-based sampling.**  Every request gets a cheap always-on
  SUMMARY record (id, status, total); full span detail is RETAINED for
  a sampled fraction plus every failed / shed / deadline-exceeded /
  slower-than-threshold request (:class:`TraceBook`) — the requests an
  operator actually asks about.  Replicas make the same decision
  hop-locally: spans piggyback when detail was requested, the hop
  failed, or the hop exceeded the threshold the gateway forwarded.
* **Bounded everything.**  Spans per trace, traces per book, and every
  per-component :class:`FlightRecorder` ring buffer are capped — a
  30-day soak holds the same memory as a 30-second one.

The ``current trace`` is thread-local (:func:`activate`): the router
activates a request's trace around its routing loop so deep helpers —
breaker filters, budget charges, chaos fault injections
(:meth:`tfmesos_tpu.chaos.FaultPlan` records every firing into the
active trace) — attribute themselves without plumbing.

Exposure: the gateway's authenticated ``trace`` op (``tfserve trace``
prints :func:`format_waterfall`), ``FleetMetrics.prometheus_text()``
behind ``tfserve --metrics-port``, and the ``fleet_trace_*`` bench
keys.  Everything here is stdlib-only and jax-free.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "TraceContext", "TraceBook", "new_trace_id",
           "activate", "current", "cur_event", "cur_elapsed", "cur_span",
           "flight", "format_waterfall"]


def new_trace_id() -> str:
    """16 hex chars of OS randomness — unguessable enough that one
    tenant cannot fish another's trace out of the book by id."""
    return os.urandom(8).hex()


class FlightRecorder:
    """A bounded, lock-cheap ring buffer of recent span/event dicts —
    one per component, so "what did the batcher just do" survives even
    when no request-level trace was retained.  Appends are one lock
    acquire and one deque append; the ring drops oldest-first."""

    def __init__(self, capacity: int = 256):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._total = 0

    def record(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._total += 1
            self._ring.append(entry)

    @property
    def total(self) -> int:
        """Entries ever recorded (the ring holds the last
        ``capacity``)."""
        with self._lock:
            return self._total

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


# Process-global per-component recorders: components grab theirs by
# name (``flight("router")``) so recording never needs plumbing.
_FLIGHTS: Dict[str, FlightRecorder] = {}
_FLIGHTS_LOCK = threading.Lock()


def flight(component: str) -> FlightRecorder:
    """The process-global flight recorder for ``component``."""
    with _FLIGHTS_LOCK:
        rec = _FLIGHTS.get(component)
        if rec is None:
            rec = _FLIGHTS[component] = FlightRecorder()
        return rec


class TraceContext:
    """One request's in-flight trace: a bounded list of span dicts,
    each ``{"component", "name", "t0", "dur", ...attrs}`` with ``t0``
    milliseconds RELATIVE to this context's creation (hop receipt) —
    hop-local by construction, JSON-ready by construction.  Thread-safe
    (the batcher's serve thread and a router worker may both append)."""

    __slots__ = ("trace_id", "detailed", "slow_ms", "dropped", "spans",
                 "_t0", "_lock", "max_spans")

    def __init__(self, trace_id: Optional[str] = None,
                 detailed: bool = False,
                 slow_ms: Optional[float] = None,
                 max_spans: int = 200):
        self.trace_id = trace_id or new_trace_id()
        self.detailed = bool(detailed)
        #: hop-local slow threshold: a hop slower than this piggybacks
        #: its detail even unsampled (the tail-based rule, applied
        #: where the latency is actually known).
        self.slow_ms = slow_ms
        self.max_spans = int(max_spans)
        self.dropped = 0
        self.spans: List[Dict[str, Any]] = []
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    # -- clocks ------------------------------------------------------------

    def elapsed_ms(self) -> float:
        """Milliseconds since this context (hop) began."""
        return (time.perf_counter() - self._t0) * 1000.0

    def rel_ms(self, perf_counter_s: float) -> float:
        """A ``time.perf_counter()`` reading as a hop-relative offset
        (same process, same clock base — never use across hosts)."""
        return (perf_counter_s - self._t0) * 1000.0

    # -- recording ---------------------------------------------------------

    def add(self, component: str, name: str, t0_ms: float,
            dur_ms: float, **attrs: Any) -> None:
        span = {"component": component, "name": name,
                "t0": round(float(t0_ms), 3),
                "dur": round(float(dur_ms), 3)}
        if attrs:
            span.update(attrs)
        with self._lock:
            if len(self.spans) >= self.max_spans:
                self.dropped += 1
                return
            self.spans.append(span)
        # The component's flight recorder sees every span too (with the
        # trace id, so a recorder entry leads back to its request).
        flight(component).record(dict(span, trace_id=self.trace_id))

    def event(self, component: str, name: str, **attrs: Any) -> None:
        """A zero-duration span at "now"."""
        self.add(component, name, self.elapsed_ms(), 0.0, **attrs)

    def span_between(self, component: str, name: str, t0_s: float,
                     t1_s: float, **attrs: Any) -> None:
        """A span from two ``time.perf_counter()`` readings taken in
        THIS process (the batcher's admit/first-token stamps)."""
        self.add(component, name, self.rel_ms(t0_s),
                 max(0.0, (t1_s - t0_s) * 1000.0), **attrs)

    def absorb(self, spans: Any, base_ms: float, **attrs: Any) -> None:
        """Graft another hop's piggybacked spans into this timeline,
        re-anchored at ``base_ms`` (the attempt's start offset on OUR
        clock) — the cross-host stitch.  Malformed entries cost
        themselves, never the trace; ``attrs`` (e.g. the replica addr)
        stamp every grafted span for attribution."""
        if not isinstance(spans, (list, tuple)):
            return
        for s in spans:
            if not isinstance(s, dict):
                continue
            try:
                t0 = base_ms + float(s.get("t0", 0.0))
                dur = float(s.get("dur", 0.0))
            except (TypeError, ValueError):
                continue
            extra = {k: v for k, v in s.items()
                     if k not in ("component", "name", "t0", "dur")}
            extra.update(attrs)
            self.add(str(s.get("component", "remote")),
                     str(s.get("name", "span")), t0, dur, **extra)

    # -- export ------------------------------------------------------------

    def export(self) -> List[Dict[str, Any]]:
        """The spans as JSON-ready dicts (hop-relative offsets) — what
        a replica piggybacks on its reply."""
        with self._lock:
            return [dict(s) for s in self.spans]

    def should_export(self, failed: bool = False) -> bool:
        """The replica-side tail rule: piggyback detail when it was
        asked for, the hop failed, or the hop ran slow."""
        return (self.detailed or failed
                or (self.slow_ms is not None
                    and self.elapsed_ms() >= self.slow_ms))


# -- thread-local current trace ---------------------------------------------

_CURRENT = threading.local()


class _Activation:
    """Context manager restoring the previous current trace on exit —
    nesting-safe (a rollout op routing inside a request's worker)."""

    __slots__ = ("_tr", "_prev")

    def __init__(self, tr: Optional[TraceContext]):
        self._tr = tr

    def __enter__(self) -> Optional[TraceContext]:
        self._prev = getattr(_CURRENT, "trace", None)
        _CURRENT.trace = self._tr
        return self._tr

    def __exit__(self, *exc) -> None:
        _CURRENT.trace = self._prev


def activate(tr: Optional[TraceContext]) -> _Activation:
    """``with activate(tr): ...`` — make ``tr`` the thread's current
    trace (``None`` deactivates; helpers then no-op)."""
    return _Activation(tr)


def current() -> Optional[TraceContext]:
    return getattr(_CURRENT, "trace", None)


def cur_event(component: str, name: str, **attrs: Any) -> None:
    """Record an event on the thread's current trace (no-op without
    one) — how deep helpers (breaker filter, budget, chaos) attribute
    themselves without plumbing."""
    tr = current()
    if tr is not None:
        tr.event(component, name, **attrs)


def cur_elapsed() -> Optional[float]:
    """The current trace's elapsed ms, or None — capture before a call
    to later :func:`cur_span` its duration."""
    tr = current()
    return tr.elapsed_ms() if tr is not None else None


def cur_span(component: str, name: str, t0_ms: Optional[float],
             **attrs: Any) -> None:
    """Close a span opened at :func:`cur_elapsed`'s reading (no-op when
    either side had no trace)."""
    tr = current()
    if tr is not None and t0_ms is not None:
        tr.add(component, name, t0_ms, tr.elapsed_ms() - t0_ms, **attrs)


# -- the gateway's trace store ----------------------------------------------


class TraceBook:
    """Finished-trace store with tail-based retention.

    Every request FINISHES into the book: a summary record always; the
    span detail is kept when the trace was head-sampled (``sample``
    fraction, or the client asked), FAILED (any non-completed status —
    sheds, deadline_exceeded, unavailable), or ran slower than
    ``slow_ms``.  ``capacity`` bounds the recent ring; detailed records
    evicted from it move to a second ``retain``-bounded ring so a flood
    of healthy traffic cannot flush the one trace that mattered."""

    def __init__(self, capacity: int = 256, retain: int = 256,
                 sample: float = 0.05, slow_ms: float = 1000.0,
                 max_spans: int = 200, rng=None):
        if not 0.0 <= sample <= 1.0:
            raise ValueError(f"sample must be in [0, 1], got {sample}")
        self.capacity = int(capacity)
        self.retain = int(retain)
        self.sample = float(sample)
        self.slow_ms = float(slow_ms)
        self.max_spans = int(max_spans)
        import random as _random
        self._rng = rng or _random.Random()
        self._lock = threading.Lock()
        self._recent: "OrderedDict[str, dict]" = OrderedDict()
        self._retained: "OrderedDict[str, dict]" = OrderedDict()
        self._finished = 0
        self._detailed = 0

    # -- lifecycle ---------------------------------------------------------

    def begin(self, trace_id: Optional[str] = None,
              want_detail: bool = False) -> TraceContext:
        """A new in-flight context: head-sampled here (client request
        for detail always wins); tail rules apply again at finish."""
        detailed = bool(want_detail) or \
            (self.sample > 0.0 and self._rng.random() < self.sample)
        return TraceContext(trace_id=trace_id, detailed=detailed,
                            slow_ms=self.slow_ms,
                            max_spans=self.max_spans)

    def finish(self, tr: TraceContext, status: str,
               **summary: Any) -> dict:
        """Close ``tr`` into a record.  ``status`` is ``"completed"``
        or the error/shed kind; anything non-completed retains full
        detail (tail-based), as does a slow or head-sampled trace."""
        total_ms = round(tr.elapsed_ms(), 3)
        keep = tr.detailed or status != "completed" \
            or total_ms >= self.slow_ms
        rec = {"trace_id": tr.trace_id, "status": status,
               "total_ms": total_ms, "detailed": keep,
               "ts": round(time.time(), 3)}
        if summary:
            rec["summary"] = dict(summary)
        if keep:
            rec["spans"] = tr.export()
            if tr.dropped:
                rec["spans_dropped"] = tr.dropped
        with self._lock:
            self._finished += 1
            if keep:
                self._detailed += 1
            # Re-finishing an id (a client-chosen id reused) keeps the
            # newest record; move-to-end keeps eviction order honest.
            self._recent.pop(tr.trace_id, None)
            self._recent[tr.trace_id] = rec
            while len(self._recent) > self.capacity:
                _, old = self._recent.popitem(last=False)
                if old.get("detailed"):
                    self._retained.pop(old["trace_id"], None)
                    self._retained[old["trace_id"]] = old
                    while len(self._retained) > self.retain:
                        self._retained.popitem(last=False)
        return rec

    # -- queries (all JSON-ready) ------------------------------------------

    def _all(self) -> List[dict]:
        with self._lock:
            return list(self._retained.values()) \
                + list(self._recent.values())

    def get(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            rec = self._recent.get(trace_id)
            if rec is None:
                rec = self._retained.get(trace_id)
            return rec

    def recent(self, n: int = 20) -> List[dict]:
        """The newest ``n`` SUMMARIES (spans stripped — the list view),
        newest first."""
        with self._lock:
            recs = list(self._recent.values())[-int(n):]
        return [{k: v for k, v in r.items() if k != "spans"}
                for r in reversed(recs)]

    def slowest(self, n: int = 5) -> List[dict]:
        """The ``n`` slowest known traces, full records, slowest
        first."""
        return sorted(self._all(), key=lambda r: -r["total_ms"])[:int(n)]

    def failed(self, n: int = 20) -> List[dict]:
        """The newest ``n`` non-completed traces, full records, newest
        first."""
        bad = [r for r in self._all() if r["status"] != "completed"]
        return sorted(bad, key=lambda r: -r["ts"])[:int(n)]

    def describe(self) -> Dict[str, int]:
        """The gateway's ``traces`` gauge: book occupancy + lifetime
        finish/detail counts."""
        with self._lock:
            return {"recent": len(self._recent),
                    "retained": len(self._retained),
                    "finished": self._finished,
                    "detailed": self._detailed}


# -- rendering ---------------------------------------------------------------


def format_waterfall(record: dict, width: int = 40) -> str:
    """One trace record as a human-readable waterfall (what ``tfserve
    trace`` prints): header, then each span in start order with its
    offset, duration, a proportional bar, and attributes."""
    tid = record.get("trace_id", "?")
    total = float(record.get("total_ms") or 0.0)
    head = (f"trace {tid}  status={record.get('status')}  "
            f"total={total:.1f}ms")
    summary = record.get("summary")
    if summary:
        head += "  " + " ".join(f"{k}={v}"
                                for k, v in sorted(summary.items()))
    spans = record.get("spans")
    if not spans:
        return head + "\n  (summary only — no span detail retained)"
    lines = [head]
    if record.get("spans_dropped"):
        lines.append(f"  ({record['spans_dropped']} spans dropped at "
                     f"the per-trace cap)")
    scale = max(total, max(float(s.get("t0", 0.0))
                           + float(s.get("dur", 0.0)) for s in spans
                           if isinstance(s, dict)), 1e-9)
    for s in sorted(spans, key=lambda s: (float(s.get("t0", 0.0)),
                                          float(s.get("dur", 0.0)))):
        t0 = float(s.get("t0", 0.0))
        dur = float(s.get("dur", 0.0))
        lo = int(round(max(0.0, t0) / scale * width))
        ln = max(1 if dur > 0 else 0,
                 int(round(dur / scale * width)))
        lo = min(lo, width - 1)
        bar = " " * lo + ("#" * ln if ln else "|")
        bar = bar[:width].ljust(width)
        attrs = {k: v for k, v in s.items()
                 if k not in ("component", "name", "t0", "dur")}
        attr_s = (" " + " ".join(f"{k}={v}"
                                 for k, v in sorted(attrs.items()))) \
            if attrs else ""
        lines.append(f"  [{bar}] {t0:9.1f}ms +{dur:8.1f}ms  "
                     f"{s.get('component', '?')}.{s.get('name', '?')}"
                     f"{attr_s}")
    return "\n".join(lines)
