"""Failure containment primitives: retry budgets and circuit breakers.

Two mechanisms the router composes into its retry loop, both jax-free
and stdlib-only like the rest of the control plane:

* :class:`RetryBudget` — a fleet-wide token-ratio budget in the gRPC
  throttling style: every retry debits one token, every delivered
  completion refills ``token_ratio`` of one, and retries are permitted
  only while the balance stays above half of ``max_tokens``.  Under a
  brown-out (most requests failing, few completing) the balance
  collapses and the fleet degrades to ~1 attempt per request instead of
  multiplying its own load ``max_retries``-fold — the retry-storm
  amplification that turns a brown-out into an outage.  Exhausted
  budget converts retryable errors into fast deterministic failures the
  client can back off on.

* :class:`CircuitBreaker` / :class:`BreakerBoard` — per-replica
  breakers with TWO trip conditions: ``failures`` consecutive failures
  (the classic crash/flap detector), and a latency outlier — the
  replica's success-latency EWMA exceeding ``latency_factor`` times the
  median EWMA of its peers.  The second is the first mechanism in the
  fleet that catches a GRAY failure: a replica that answers every
  heartbeat on time (so the registry reports it alive) but serves 100x
  slow.  An open breaker excludes the replica from every router pick;
  after ``cooldown_s`` it goes half-open and admits exactly ONE probe
  request — success closes it, failure re-opens with exponential
  backoff (capped at ``max_cooldown_s``).  Breakers mark nothing dead:
  the registry keeps its own liveness truth, and a recovered replica
  re-enters routing through its probe, not through operator action.

Both are exported through the gateway's metrics snapshot (the
``breakers`` and ``retry_budget`` gauges plus the router's counters) —
during a brown-out they are the on-call's first questions.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

__all__ = ["RetryBudget", "BreakerConfig", "CircuitBreaker",
           "BreakerBoard", "CLOSED", "OPEN", "HALF_OPEN",
           "DEFAULT_MAX_TOKENS", "DEFAULT_TOKEN_RATIO"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: Retry-budget defaults (sweepable as ``budget.max_tokens`` /
#: ``budget.token_ratio`` in ``tfserve simulate``).  10 tokens of
#: runway absorbs a short failure burst without throttling; a 0.1
#: refill per delivered completion means sustained retries above ~10%
#: of throughput drain the budget and failovers stop — the simulator's
#: ``soak-replay`` scenario holds retry amplification under 1.5
#: through a replica death at these values, and a brown-out sweep
#: (``budget.token_ratio=0.05,0.1,0.5``) shows 0.5 re-arming the storm
#: while 0.05 starves legitimate failovers.
DEFAULT_MAX_TOKENS = 10.0
DEFAULT_TOKEN_RATIO = 0.1


class RetryBudget:
    """Fleet-wide retry budget (gRPC-throttling style token ratio).

    ``try_retry()`` is consulted before every failover: it debits one
    token and answers whether the balance (pre-debit) was above half of
    ``max_tokens`` — so sustained failures drain the budget even while
    it still says yes, and the cutoff arrives deterministically.
    ``on_success()`` refills ``token_ratio`` tokens per delivered
    completion, so a healthy fleet recovers its budget at a rate
    proportional to real throughput, never by wall clock (a wall-clock
    refill would re-arm the storm on a schedule).
    """

    def __init__(self, max_tokens: float = DEFAULT_MAX_TOKENS,
                 token_ratio: float = DEFAULT_TOKEN_RATIO):
        if max_tokens <= 0 or token_ratio <= 0:
            raise ValueError(
                f"max_tokens and token_ratio must be > 0, got "
                f"{max_tokens} / {token_ratio}")
        self.max_tokens = float(max_tokens)
        self.token_ratio = float(token_ratio)
        self._tokens = self.max_tokens
        self._lock = threading.Lock()

    def try_retry(self) -> bool:
        with self._lock:
            allowed = self._tokens > self.max_tokens / 2.0
            self._tokens = max(0.0, self._tokens - 1.0)
            return allowed

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.token_ratio)

    def level(self) -> float:
        """Remaining budget as a 0..1 fraction (the gateway's
        ``retry_budget`` gauge; retries stop below 0.5)."""
        with self._lock:
            return self._tokens / self.max_tokens


@dataclasses.dataclass
class BreakerConfig:
    """Per-replica circuit-breaker thresholds (docs/SERVING.md
    "Deadlines & failure containment").

    ``failures`` consecutive failures trip; a success-latency EWMA above
    ``latency_factor`` times the median of the peers' EWMAs (with at
    least ``min_samples`` observations on each side and an absolute
    ``latency_floor_ms`` so microsecond-scale jitter can never trip)
    trips too — the gray-failure detector.  An open breaker waits
    ``cooldown_s`` before its single half-open probe; every failed probe
    doubles the wait up to ``max_cooldown_s``.

    Every threshold here is sweepable by path in the fleet simulator
    (``tfserve simulate soak-replay --sweep breaker.latency_factor=
    2,4,8`` — docs/SIMULATOR.md): ``latency_factor=4`` is the value at
    which the ``soak-replay`` scenario isolates a 20x-slow gray replica
    within its traffic warmup while a healthy fleet's natural p99/p50
    spread (~2-3x under bursty arrivals) never trips; 2 flaps on load
    skew, 8 lets the gray replica serve for multiples of the detection
    window.  ``failures=3`` / ``cooldown_s=2`` come from the same
    scenario's SIGKILL phase: the dead replica is out of every
    candidate set before the heartbeat sweeper even marks it."""

    failures: int = 3
    cooldown_s: float = 2.0
    max_cooldown_s: float = 30.0
    latency_factor: float = 4.0
    latency_floor_ms: float = 50.0
    min_samples: int = 5
    ewma_alpha: float = 0.3

    def __post_init__(self):
        if self.failures < 1:
            raise ValueError(f"failures must be >= 1, got {self.failures}")
        if self.cooldown_s <= 0 or self.max_cooldown_s < self.cooldown_s:
            raise ValueError(
                f"need 0 < cooldown_s <= max_cooldown_s, got "
                f"{self.cooldown_s} / {self.max_cooldown_s}")
        if self.latency_factor <= 1.0:
            raise ValueError(f"latency_factor must be > 1, got "
                             f"{self.latency_factor}")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got "
                             f"{self.ewma_alpha}")


class CircuitBreaker:
    """One replica's breaker state (owned by a :class:`BreakerBoard`,
    which holds the lock and the peer context for the latency check)."""

    __slots__ = ("addr", "state", "consecutive_failures", "ewma_ms",
                 "samples", "trips", "open_until", "cooldown",
                 "probing_since", "reason")

    def __init__(self, addr: str):
        self.addr = addr
        self.state = CLOSED
        self.consecutive_failures = 0
        self.ewma_ms = 0.0
        self.samples = 0
        self.trips = 0
        self.open_until = 0.0
        self.cooldown = 0.0          # current backoff (set at first trip)
        self.probing_since = 0.0
        self.reason = ""

    def describe(self) -> Dict[str, object]:
        return {"state": self.state,
                "ewma_ms": round(self.ewma_ms, 3),
                "consecutive_failures": self.consecutive_failures,
                "trips": self.trips,
                "reason": self.reason}


class BreakerBoard:
    """All replica breakers plus the cross-replica latency context.

    The router consults :meth:`eligible` when building candidate sets
    (side-effect-free — a filtered-out candidate must not consume the
    half-open probe slot), calls :meth:`on_dispatch` for the ONE replica
    it actually picked (which is what claims the probe), and reports
    every outcome through :meth:`record_success` /
    :meth:`record_failure`.  Trips are evaluated inside the records, so
    there is no sweeper thread to race.
    """

    def __init__(self, config: Optional[BreakerConfig] = None,
                 clock=time.monotonic):
        self.config = config or BreakerConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.trips = 0
        self.latency_trips = 0
        self.recoveries = 0
        # Count of breakers NOT in CLOSED state: the router's per-pick
        # filter short-circuits to "everyone eligible" while this is 0
        # (the overwhelmingly common state) instead of querying every
        # candidate — O(1) instead of O(replicas) per request.
        self._nonclosed = 0

    def all_closed(self) -> bool:
        """True while every breaker is CLOSED — read lock-free (a
        single int; a stale read costs one pick a full filter pass,
        never a wrong routing decision, since the filter re-checks
        every candidate under the lock)."""
        return self._nonclosed == 0

    def _get(self, addr: str) -> CircuitBreaker:
        b = self._breakers.get(addr)
        if b is None:
            b = self._breakers[addr] = CircuitBreaker(addr)
        return b

    # -- routing-side queries ----------------------------------------------

    def eligible(self, addr: str) -> bool:
        """Whether the router may CANDIDATE this replica right now.
        Closed: yes.  Open: only once the cooldown has elapsed (the
        pick that follows becomes the probe).  Half-open: only while no
        probe is in flight — one request at a time tests a suspect
        replica, never a thundering herd (a stale probe older than the
        max cooldown is presumed lost and releases the slot)."""
        now = self._clock()
        with self._lock:
            b = self._breakers.get(addr)
            if b is None or b.state == CLOSED:
                return True
            if b.state == OPEN:
                return now >= b.open_until
            # HALF_OPEN
            return (not b.probing_since
                    or now - b.probing_since > self.config.max_cooldown_s)

    def on_dispatch(self, addr: str) -> bool:
        """The router picked ``addr``: if its breaker was waiting for a
        probe, THIS request claims it — returns True for exactly one
        caller (the probe), False for everyone else.  The caller
        threads the flag back into :meth:`record_success` /
        :meth:`record_failure` so only the sanctioned probe's outcome
        can close or re-open the breaker; a pre-trip straggler (or a
        request that raced the eligible()->pick window) merely feeds
        the statistics.  That race window — several workers passing
        ``eligible`` before the first reaches here — can leak a couple
        of extra requests onto a suspect replica, but it is pick-to-
        dispatch small and none of the leakers can flip the state."""
        now = self._clock()
        with self._lock:
            b = self._breakers.get(addr)
            if b is None or b.state == CLOSED:
                return False
            if b.state == OPEN and now >= b.open_until:
                b.state = HALF_OPEN
                b.probing_since = 0.0
            if b.state == HALF_OPEN and (
                    not b.probing_since
                    or now - b.probing_since > self.config.max_cooldown_s):
                b.probing_since = now
                return True
            return False

    # -- outcome records ---------------------------------------------------

    def _trip(self, b: CircuitBreaker, now: float, reason: str) -> None:
        if b.state == CLOSED:
            self._nonclosed += 1
        b.state = OPEN
        b.cooldown = (self.config.cooldown_s if not b.cooldown
                      else min(2.0 * b.cooldown,
                               self.config.max_cooldown_s))
        b.open_until = now + b.cooldown
        b.probing_since = 0.0
        b.trips += 1
        b.reason = reason
        self.trips += 1
        if reason == "latency_outlier":
            self.latency_trips += 1

    def record_success(self, addr: str, latency_ms: float,
                       probe: bool = False) -> None:
        """One completed call: closes a half-open breaker when it was
        THE probe (the ``on_dispatch`` claim rides back in ``probe`` —
        a pre-trip straggler landing mid-probe must not close the gate
        the probe is still testing), resets the consecutive-failure
        count, folds the latency into the EWMA, and evaluates the
        latency-outlier trip against the peer median — the check runs
        on SUCCESSES because a gray-slow replica fails nothing; its
        requests all complete, just 100x late."""
        now = self._clock()
        cfg = self.config
        with self._lock:
            b = self._get(addr)
            if b.state == HALF_OPEN and probe:
                # The probe came back: the replica serves again.  The
                # cooldown is NOT reset — a flapping replica re-trips
                # onto its grown backoff.  A LATENCY trip additionally
                # resets the EWMA history: the stale high average must
                # not re-trip the breaker off one fast probe (a
                # transient spike — e.g. a cold compile — would
                # otherwise lock a healthy replica out for many grown
                # cooldowns); a replica that is STILL slow re-earns its
                # trip over min_samples fresh observations.
                if b.reason == "latency_outlier":
                    b.ewma_ms = 0.0
                    b.samples = 0
                b.state = CLOSED
                self._nonclosed -= 1
                b.probing_since = 0.0
                b.reason = ""
                self.recoveries += 1
            b.consecutive_failures = 0
            if b.samples == 0:
                b.ewma_ms = float(latency_ms)
            else:
                b.ewma_ms += cfg.ewma_alpha * (float(latency_ms)
                                               - b.ewma_ms)
            b.samples += 1
            if b.state != CLOSED:
                # A straggler of a pre-trip dispatch while OPEN: its
                # latency still feeds the EWMA, but only the cooldown-
                # gated probe may close (or re-trip) the breaker.
                return
            if b.samples < cfg.min_samples \
                    or b.ewma_ms < cfg.latency_floor_ms:
                return
            peers = [p.ewma_ms for p in self._breakers.values()
                     if p is not b and p.samples >= cfg.min_samples]
            if not peers:
                return          # no baseline: an outlier needs peers
            peers.sort()
            median = peers[len(peers) // 2]
            if median > 0 and b.ewma_ms > cfg.latency_factor * median:
                self._trip(b, now, "latency_outlier")

    def record_failure(self, addr: str, probe: bool = False) -> None:
        """One failed call (timeout, connection loss, replica internal
        error — never a deterministic bad_request): a failed half-open
        PROBE re-opens immediately with doubled cooldown (a straggler
        failing mid-probe only advances the statistics — the probe in
        flight still decides); otherwise the consecutive-failure count
        advances toward its trip."""
        now = self._clock()
        with self._lock:
            b = self._get(addr)
            b.consecutive_failures += 1
            if b.state == HALF_OPEN:
                if probe:
                    self._trip(b, now, "probe_failed")
                return
            if b.state == CLOSED \
                    and b.consecutive_failures >= self.config.failures:
                self._trip(b, now, "consecutive_failures")

    # -- observability -----------------------------------------------------

    def state_of(self, addr: str) -> str:
        with self._lock:
            b = self._breakers.get(addr)
            return b.state if b is not None else CLOSED

    def open_addrs(self) -> List[str]:
        now = self._clock()
        with self._lock:
            return [a for a, b in self._breakers.items()
                    if b.state == OPEN and now < b.open_until]

    def summary(self) -> Dict[str, object]:
        """The small dict the gateway exports as its ``breakers`` gauge
        (the report line prints it verbatim)."""
        with self._lock:
            return {
                "open": sorted(a for a, b in self._breakers.items()
                               if b.state == OPEN),
                "half_open": sorted(a for a, b in self._breakers.items()
                                    if b.state == HALF_OPEN),
                "trips": self.trips,
                "latency_trips": self.latency_trips,
                "recoveries": self.recoveries,
            }

    def describe(self) -> Dict[str, dict]:
        """Per-replica breaker detail (state, EWMA, failure streak,
        trip count and reason)."""
        with self._lock:
            return {a: b.describe() for a, b in self._breakers.items()}
