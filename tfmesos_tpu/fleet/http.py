"""HTTP/1.1 + SSE ingress for the fleet gateway (docs/SERVING.md
"HTTP/SSE edge").

The wire protocol is the fleet's native tongue, but every standard
load-generation and client tool speaks HTTP.  :class:`HttpIngress` is a
minimal OpenAI-style adapter that rides the SAME ``WireServer`` event
loop as the wire port (``WireServer.add_ingress``): one selector
thread, the same write-buffer backpressure, and the same
slow-loris/byte-bound discipline the wire path enforces pre-auth —
except here the bounds are HTTP-shaped (request-head and body caps,
header/body receive deadlines swept by the loop).

Surface::

    GET  /healthz            -> 200 {"ok": true}
    POST /v1/completions     -> one generation request

The JSON body maps onto the gateway's internal ``generate`` op — the
same admission, WFQ, tracing, routing, and metering path wire clients
take (the adapter IS a gateway client, not a second front door):

- ``prompt``: a list of token ids, or a string (encoded to its UTF-8
  bytes — the demo-model convention; real deployments front a
  tokenizer).
- ``max_tokens`` (or ``max_new_tokens``): decode budget.
- ``stream``: ``true`` answers ``text/event-stream`` SSE frames off the
  exactly-once token relay; ``false``/absent answers one JSON body.
- ``stop_token``, ``model``, ``session``, ``priority``,
  ``deadline_ms``, ``trace``: as in ``FleetClient.generate``.  The
  ``x-model`` / ``x-session`` / ``x-priority`` / ``x-deadline-ms``
  headers are body-absent fallbacks (proxy-injectable routing).

Error mapping: admission/routing error kinds become HTTP statuses
(``overloaded``/``rate_limited`` -> 429 with Retry-After,
``deadline_exceeded`` -> 504, ``unavailable``/``wrong_model`` -> 503,
``bad_request`` -> 400, else 500).  Mid-stream errors arrive as a final
SSE ``error`` event — the status line already went out.

Connections are KEPT ALIVE across requests (HTTP/1.1 default;
HTTP/1.0 opts in with ``Connection: keep-alive``, either side opts out
with ``Connection: close``): after a JSON response the parser re-arms
for the next request head on the same socket, so a load tool's pooled
connection pays the TCP+dial cost once, not per request.  SSE streams
and error responses stay terminal — a stream has no delimiter to
re-sync past, and an error leaves parser state ambiguous.  An idle
keep-alive connection is swept by the same header-deadline discipline
as a fresh one.  A client disconnect mid-stream is observed by the
token relay (``closed`` below) and cancels the replica-side row
through the router's one-way ``cancel`` op — a walked-away user stops
billing and frees pages within a decode tick.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

from tfmesos_tpu.utils.logging import get_logger

__all__ = ["HttpIngress", "MAX_HEADER_BYTES", "MAX_BODY_BYTES"]

# Pre-auth byte bounds (the HTTP analog of wire.MAX_FRAME): nothing
# past these ever buffers for an unauthenticated peer.
MAX_HEADER_BYTES = 16 * 1024
MAX_BODY_BYTES = 8 * 1024 * 1024

# Receive deadlines (the slow-loris discipline): a peer that trickles
# its request head/body is swept closed by the event loop.
HEADER_TIMEOUT_S = 10.0
BODY_TIMEOUT_S = 30.0

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 411: "Length Required",
    413: "Payload Too Large", 429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    500: "Internal Server Error", 503: "Service Unavailable",
    504: "Gateway Timeout",
}

# Gateway error ``kind`` -> HTTP status.
_KIND_STATUS = {
    "bad_request": 400,
    "not_found": 404,
    "overloaded": 429,
    "rate_limited": 429,
    "deadline_exceeded": 504,
    "unavailable": 503,
    "wrong_model": 503,
    "internal": 500,
}


def _response_bytes(status: int, body_obj: Any,
                    content_type: str = "application/json",
                    extra: Tuple[Tuple[str, str], ...] = (),
                    keep: bool = False) -> bytes:
    body = json.dumps(body_obj).encode("utf-8")
    head = (f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
            f"Content-Type: {content_type}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: {'keep-alive' if keep else 'close'}\r\n"
            + "".join(f"{k}: {v}\r\n" for k, v in extra)
            + "\r\n")
    return head.encode("latin-1") + body


def _sse_event(obj: Any) -> bytes:
    data = obj if isinstance(obj, str) else json.dumps(obj)
    return f"data: {data}\n\n".encode("utf-8")


_SSE_HEAD = (b"HTTP/1.1 200 OK\r\n"
             b"Content-Type: text/event-stream\r\n"
             b"Cache-Control: no-cache\r\n"
             b"Connection: close\r\n\r\n")


class _BadRequest(Exception):
    """Parse-level rejection carrying its HTTP status."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


class _HttpReply:
    """Duck-typed stand-in for the gateway's wire-client connection.

    The gateway's handler/worker path only ever calls ``send(dict)``
    and (through the stream relay's cancel probe) reads ``closed`` —
    this shim translates those reply dicts into HTTP bytes on the
    ingress connection: token partials become SSE frames, the final
    completion becomes the JSON body (or the SSE tail + ``[DONE]``),
    errors become statuses.  ``send`` is called from gateway worker
    threads AND the event loop (synchronous admission rejections), so
    it serializes under its own lock; the byte writes ride
    ``WireConn.send_bytes`` which is thread-safe and buffered."""

    def __init__(self, conn, stream: bool, keep: bool = False,
                 on_done=None):
        self._conn = conn
        self.stream = bool(stream)
        # Keep-alive: a non-stream completion re-arms the connection
        # for the next request via ``on_done`` instead of closing.
        self._keep = bool(keep) and not self.stream
        self._on_done = on_done
        self.peer = getattr(conn, "peer", "http")
        self._lock = threading.Lock()
        self._started = False       # SSE status line sent
        self._done = False
        self._sent = 0              # token high-water mark (dedup)

    @property
    def closed(self) -> bool:
        # The stream relay's disconnect probe: True once the HTTP
        # client went away (the loop closed the WireConn) — upstream
        # this cancels the replica-side row.
        return bool(self._conn.closed)

    # -- gateway-facing ----------------------------------------------------

    def send(self, obj: Any) -> bool:
        if not isinstance(obj, dict) or self._conn.closed:
            return False
        op = obj.get("op")
        with self._lock:
            if self._done:
                return False
            if op == "tokens":
                return self._tokens(obj)
            if op == "completion":
                return self._completion(obj)
            if op == "error":
                return self._error(obj)
        return False

    # -- internals (all under self._lock) ----------------------------------

    def _new_tokens(self, obj: Dict[str, Any]) -> list:
        toks = obj.get("tokens")
        if not isinstance(toks, list) or not toks:
            return []
        off = obj.get("off")
        off = int(off) if isinstance(off, (int, float)) \
            and not isinstance(off, bool) else 0
        if off + len(toks) <= self._sent:
            return []
        new = toks[max(0, self._sent - off):]
        self._sent = off + len(toks)
        return new

    def _ensure_sse(self) -> None:
        if not self._started:
            self._started = True
            self._conn.send_bytes(_SSE_HEAD)

    def _tokens(self, obj: Dict[str, Any]) -> bool:
        if not self.stream:
            return True             # relay installed only for streams
        new = self._new_tokens(obj)
        if not new:
            return True
        off = self._sent - len(new)
        self._ensure_sse()
        return self._conn.send_bytes(_sse_event(
            {"tokens": [int(t) for t in new], "off": off}))

    def _completion(self, obj: Dict[str, Any]) -> bool:
        self._done = True
        toks = [int(t) for t in (obj.get("tokens") or [])]
        meta = {"ttft_ms": obj.get("ttft_ms"),
                "total_ms": obj.get("total_ms"),
                "trace_id": obj.get("trace_id")}
        if self.stream:
            # The completion carries the FULL list; the high-water
            # dedup emits exactly the not-yet-streamed tail.
            tail = self._new_tokens({"tokens": toks, "off": 0})
            self._ensure_sse()
            if tail:
                self._conn.send_bytes(_sse_event(
                    {"tokens": tail, "off": self._sent - len(tail)}))
            done = dict(meta)
            done["done"] = True
            done["n_tokens"] = len(toks)
            ok = self._conn.send_bytes(_sse_event(done)
                                       + _sse_event("[DONE]"))
        else:
            body = {"object": "completion", "tokens": toks}
            body.update(meta)
            ok = self._conn.send_bytes(
                _response_bytes(200, body, keep=self._keep))
            if ok and self._keep and self._on_done is not None:
                # Connection reuse: hand the socket back to the parser
                # for the next request instead of closing.
                self._on_done()
                return ok
        self._conn.close()
        return ok

    def _error(self, obj: Dict[str, Any]) -> bool:
        self._done = True
        kind = str(obj.get("kind") or "internal")
        status = _KIND_STATUS.get(kind, 500)
        err = {"error": {"type": kind,
                         "message": str(obj.get("error") or kind),
                         "trace_id": obj.get("trace_id")}}
        if self._started:
            # SSE already underway: the status line is history — the
            # error arrives as the stream's terminal event.
            ok = self._conn.send_bytes(_sse_event(err)
                                       + _sse_event("[DONE]"))
        else:
            extra = (("Retry-After", "1"),) if status == 429 else ()
            ok = self._conn.send_bytes(
                _response_bytes(status, err, extra=extra))
        self._conn.close()
        return ok


class HttpIngress:
    """Factory wired into ``WireServer.add_ingress``: one
    :class:`_HttpConn` protocol object per accepted connection,
    dispatching parsed requests into ``gateway.handle_ingress``."""

    def __init__(self, gateway, max_body: int = MAX_BODY_BYTES,
                 max_header: int = MAX_HEADER_BYTES,
                 header_timeout: float = HEADER_TIMEOUT_S,
                 body_timeout: float = BODY_TIMEOUT_S):
        self.gateway = gateway
        self.max_body = int(max_body)
        self.max_header = int(max_header)
        self.header_timeout = float(header_timeout)
        self.body_timeout = float(body_timeout)
        self.log = get_logger("tfmesos_tpu.fleet.http")

    def __call__(self, conn) -> "_HttpConn":
        return _HttpConn(self, conn)


class _HttpConn:
    """Per-connection incremental HTTP/1.1 parser (request head ->
    Content-Length body -> dispatch), KEPT ALIVE across requests: a
    finished JSON response re-arms the parser for the next head on the
    same socket (bytes a pipelining client sent early are held,
    bounded, until then).  Parsing runs on the event-loop thread and —
    for the re-arm after a worker-thread reply — on that worker, so
    every state transition holds ``_plock``.  Rejection is either an
    explicit error response + close, or a raise (the loop drops the
    connection)."""

    def __init__(self, ingress: HttpIngress, conn):
        self.ingress = ingress
        self.conn = conn
        self._buf = bytearray()
        self._state = "head"
        self._need = 0
        self._keep = False          # this request's keep-alive verdict
        self._headers: Dict[str, str] = {}
        self._reply: Optional[_HttpReply] = None
        # RLock: a reply that completes synchronously inside
        # _dispatch (an admission rejection on the loop thread)
        # re-enters through _request_done.
        self._plock = threading.RLock()
        # Slow-loris bound on the request head, swept by the loop.
        conn.deadline = time.monotonic() + ingress.header_timeout
        conn._server._watch(conn)

    # -- WireServer protocol interface -------------------------------------

    def data_received(self, data: bytes) -> None:
        with self._plock:
            self._buf += data
            if self._state == "done":
                # A reply is in flight: hold the pipelined next
                # request (bounded) until _request_done re-arms.
                if len(self._buf) > (self.ingress.max_header
                                     + self.ingress.max_body):
                    self.conn.close()
                return
            self._process()

    def _process(self) -> None:
        """Drive the parse over whatever is buffered (``_plock``
        held).  Loops so a keep-alive healthz — or a pipelined next
        request — completes without waiting for more socket bytes."""
        while True:
            if self._state == "head":
                idx = self._buf.find(b"\r\n\r\n")
                if idx < 0:
                    if len(self._buf) > self.ingress.max_header:
                        self._reject(431,
                                     "request head exceeds "
                                     f"{self.ingress.max_header} bytes")
                    return
                head = bytes(self._buf[:idx])
                del self._buf[:idx + 4]
                try:
                    self._parse_head(head)
                except _BadRequest as e:
                    self._reject(e.status, str(e))
                    return
                continue    # head may have answered and re-armed
            if self._state == "body":
                if len(self._buf) < self._need:
                    return
                # Slice EXACTLY the declared body; trailing bytes are
                # the pipelined next request, not an error.
                body = bytes(self._buf[:self._need])
                del self._buf[:self._need]
                self._state = "done"
                self.conn.deadline = None
                self._dispatch(body)
            return

    def _request_done(self) -> None:
        """A keep-alive response finished (worker thread or loop):
        re-arm for the next request and drain anything pipelined."""
        with self._plock:
            if self.conn.closed or self._state != "done":
                return
            self._next_request()
            self._process()

    def _next_request(self) -> None:
        """Reset per-request parser state (``_plock`` held)."""
        self._state = "head"
        self._need = 0
        self._keep = False
        self._headers = {}
        self._reply = None
        self.conn.deadline = (time.monotonic()
                              + self.ingress.header_timeout)
        self.conn._server._watch(self.conn)

    def on_close(self) -> None:
        # Nothing to release here: the reply shim reads conn.closed,
        # and the stream relay's cancel probe does the row release.
        pass

    # -- parsing -----------------------------------------------------------

    def _parse_head(self, head: bytes) -> None:
        try:
            text = head.decode("latin-1")
        except UnicodeDecodeError:      # pragma: no cover - latin-1 total
            raise _BadRequest(400, "undecodable request head")
        lines = text.split("\r\n")
        parts = lines[0].split(" ")
        if len(parts) != 3 or not parts[0].isalpha() \
                or not parts[1].startswith("/") \
                or parts[2] not in ("HTTP/1.1", "HTTP/1.0"):
            raise _BadRequest(400, f"malformed request line "
                                   f"{lines[0][:80]!r}")
        method, path = parts[0].upper(), parts[1]
        headers: Dict[str, str] = {}
        for ln in lines[1:]:
            if not ln:
                continue
            name, sep, value = ln.partition(":")
            if not sep or not name or name != name.strip() \
                    or any(c in name for c in " \t"):
                raise _BadRequest(400, f"malformed header {ln[:80]!r}")
            headers[name.lower()] = value.strip()
        self._headers = headers
        # Keep-alive verdict: HTTP/1.1 defaults on, HTTP/1.0 defaults
        # off; either side's ``Connection: close`` wins.
        conn_tok = headers.get("connection", "").lower()
        if parts[2] == "HTTP/1.1":
            self._keep = "close" not in conn_tok
        else:
            self._keep = "keep-alive" in conn_tok
        path = path.split("?", 1)[0]
        if path == "/healthz":
            self._respond(200, {"ok": True}, keep=self._keep)
            return
        if path != "/v1/completions":
            raise _BadRequest(404, f"unknown path {path[:80]!r}")
        if method != "POST":
            raise _BadRequest(405, f"{method} not allowed on {path}")
        if "transfer-encoding" in headers:
            raise _BadRequest(400, "chunked bodies are not supported")
        cl = headers.get("content-length")
        if cl is None:
            raise _BadRequest(411, "Content-Length required")
        try:
            need = int(cl)
        except ValueError:
            raise _BadRequest(400, f"bad Content-Length {cl!r}") from None
        if need <= 0:
            raise _BadRequest(400, "empty body")
        if need > self.ingress.max_body:
            # The pre-auth bound: reject on the DECLARED size, before a
            # single body byte buffers.
            raise _BadRequest(413, f"body of {need} bytes exceeds the "
                                   f"{self.ingress.max_body} byte bound")
        self._need = need
        self._state = "body"
        self.conn.deadline = time.monotonic() + self.ingress.body_timeout

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, body: bytes) -> None:
        try:
            obj = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError):
            self._reject(400, "body is not valid JSON")
            return
        if not isinstance(obj, dict):
            self._reject(400, "body must be a JSON object")
            return
        try:
            msg = self._build_generate(obj)
        except _BadRequest as e:
            self._reject(e.status, str(e))
            return
        self._reply = _HttpReply(self.conn, stream=bool(msg.get("stream")),
                                 keep=self._keep,
                                 on_done=self._request_done)
        # Same internal submit path as a wire client's generate: the
        # gateway's admission/tracing/routing/metering see no
        # difference, and every reply rides the shim back out as HTTP.
        self.ingress.gateway.handle_ingress(self._reply, msg)

    def _build_generate(self, obj: Dict[str, Any]) -> Dict[str, Any]:
        h = self._headers
        prompt = obj.get("prompt")
        if isinstance(prompt, str) and prompt:
            prompt = list(prompt.encode("utf-8"))
        if not isinstance(prompt, list) or not prompt:
            raise _BadRequest(400, "prompt must be a non-empty list of "
                                   "token ids or a string")
        try:
            prompt = [int(t) for t in prompt]
        except (TypeError, ValueError):
            raise _BadRequest(400, "prompt tokens must be ints") from None
        mt = obj.get("max_tokens", obj.get("max_new_tokens", 16))
        if not isinstance(mt, int) or isinstance(mt, bool) or mt < 1:
            raise _BadRequest(400, f"max_tokens must be a positive int, "
                                   f"got {mt!r}")
        msg: Dict[str, Any] = {"op": "generate", "id": 1,
                               "prompt": prompt, "max_new_tokens": mt,
                               "stop_token": obj.get("stop_token")}
        if obj.get("stream"):
            msg["stream"] = True
        prio = obj.get("priority", h.get("x-priority"))
        if isinstance(prio, str) and prio:
            msg["priority"] = prio
        dl = obj.get("deadline_ms")
        if dl is None and "x-deadline-ms" in h:
            try:
                dl = float(h["x-deadline-ms"])
            except ValueError:
                dl = None           # a malformed header costs the field
        if isinstance(dl, (int, float)) and not isinstance(dl, bool) \
                and dl > 0:
            msg["deadline_ms"] = float(dl)
        sid = obj.get("session", h.get("x-session"))
        if isinstance(sid, str) and sid:
            msg["session"] = sid
        model = obj.get("model", h.get("x-model"))
        if isinstance(model, str) and model:
            msg["model"] = model
        tr = obj.get("trace")
        if tr:
            msg["trace"] = tr if isinstance(tr, str) else True
        return msg

    # -- responses ---------------------------------------------------------

    def _respond(self, status: int, body_obj: Any,
                 keep: bool = False) -> None:
        """Answer in-parse (``_plock`` held): healthz keeps the
        connection when the client does; rejections always close —
        after a parse error the stream position is ambiguous."""
        if keep:
            if self.conn.send_bytes(
                    _response_bytes(status, body_obj, keep=True)):
                self._state = "done"
                self._next_request()
                return
        self._state = "done"
        self.conn.deadline = None
        self.conn.send_bytes(_response_bytes(status, body_obj))
        self.conn.close()

    def _reject(self, status: int, message: str) -> None:
        self._respond(status, {"error": {"type": "http",
                                         "message": message}})
