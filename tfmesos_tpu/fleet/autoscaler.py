"""Fleet autoscaler: a feedback loop that sizes each serving tier.

The fleet is no longer a launch-time constant: this control loop reads
per-tier load signals every tick and converges each tier's replica
count toward a target inside ``[min_replicas, max_replicas]`` bounds —
the replica-membership-as-runtime-property stance of TF-Replicator
(PAPERS.md), applied to serving.

Signals (all already flowing before this module existed):

* **Prompt-bearing tiers** (unified, prefill) scale on the WINDOWED p99
  of the gateway's ``queue_wait_ms`` histogram — the interval between
  two control ticks, not the lifetime percentile, so the loop reacts to
  load that exists now rather than chasing a surge that ended minutes
  ago — plus tier utilization (self-reported outstanding / advertised
  capacity from registry heartbeats).
* **The decode tier** scales on aggregate KV-page headroom per alive
  replica (the heartbeat field decode routing already places by):
  decode replicas run out of *pages*, not CPU, long before their row
  counts saturate.

Actuation goes through the fleet's dynamic launcher:

* **Scale up** launches ONE new Mode-B replica task per tick (the same
  command line the tier booted with, ``--warmup`` included, so the
  newcomer registers ``warming`` and never takes traffic cold).
* **Scale down** picks the least-loaded alive replica and announces a
  PINNED drain at the registry (``begin_drain`` — drain-for-scale-down:
  the healthy victim keeps heartbeating plain alive beats while its
  in-flight work flushes, and those beats must not revive it), asks it
  to MIGRATE its in-flight rows (suspend → the router re-places each
  exported KV artifact on a surviving replica, resuming mid-stream;
  docs/SERVING.md "Priorities, preemption & migration"), then kills
  the task only once its outstanding count reaches zero (or the drain
  deadline passes).  In-flight requests are never shed.
* **Convergence doubles as self-healing**: a replica task that dies is
  dropped from the scheduler's table, actual falls below target, and
  the next tick relaunches it — one per tick, so a crash loop churns at
  the control cadence, not as fast as fork can go.

Stability guards: hysteresis (the up and down thresholds form a dead
band), separate per-tier cooldowns for each direction, at most one
pending drain per tier, and a hard invariant that a routable tier is
never drained below one alive replica no matter what the signals say.
Every decision lands in the log and in the ``autoscaler`` gauge
(target / actual / last_action per tier).

Determinism for tests: the clock and the signal source are both
injectable (the ``chaos.py`` discipline) — a fake-signal test drives
``step()`` by hand and asserts the exact launch/drain/kill sequence,
no timing races involved.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Dict, Optional

from tfmesos_tpu.fleet.metrics import Histogram
from tfmesos_tpu.fleet.registry import ALIVE, DEAD, DECODE, KV
from tfmesos_tpu.utils.logging import get_logger

__all__ = ["AutoscalerConfig", "FleetAutoscaler"]


@dataclasses.dataclass
class AutoscalerConfig:
    """Knobs of the control loop (docs/SERVING.md "Autoscaling").

    Every field is sweepable by path in the fleet simulator
    (``tfserve simulate surge --sweep autoscaler.queue_wait_hi_ms=
    200,500,2000`` — docs/SIMULATOR.md), which is where these defaults
    earn their values: the ``surge`` scenario (4x arrival-rate step
    against a 4-replica tier) converges to the new steady size without
    overshoot at the hysteresis band below, while a narrowed band
    (``queue_wait_lo_ms`` close to ``hi``) visibly flaps
    launch/drain/launch on the same trace, and a widened one rides the
    surge out without scaling at all."""

    #: seconds between control ticks (the loop's cadence).
    interval: float = 1.0
    #: prompt tiers scale UP when the windowed queue-wait p99 crosses
    #: this; the matching ``lo`` bound arms scale-down — the gap between
    #: them is the hysteresis dead band that keeps the loop from
    #: flapping on a noisy signal.
    queue_wait_hi_ms: float = 500.0
    queue_wait_lo_ms: float = 50.0
    #: tier utilization (self-reported outstanding / advertised
    #: capacity) bounds, same dead-band structure.
    util_hi: float = 0.75
    util_lo: float = 0.25
    #: decode tier: scale UP when average free KV pages per alive
    #: replica dip below ``lo``; ``hi`` (with low utilization) arms
    #: scale-down.
    kv_headroom_lo: float = 8.0
    kv_headroom_hi: float = 64.0
    #: fleet KV-tier occupancy (RAM-tier bytes used / budget, summed
    #: over every tiered replica): above this the tier is evicting
    #: parked artifacts, so it BLOCKS scale-down (a drained replica's
    #: tier capacity evicts more) and — together with the hit-rate
    #: floor below — arms scale-up even when queue wait looks calm.
    kv_tier_occupancy_hi: float = 0.9
    #: windowed KV-tier hit-rate floor: a saturated tier whose
    #: between-ticks hit rate sits below this is THRASHING (traffic
    #: still wants what eviction throws away) — more replicas mean
    #: more aggregate tier RAM, so that combination scales up.
    kv_tier_hit_rate_lo: float = 0.2
    #: per-tier cooldowns, one per direction: growing again right after
    #: growing is cheap to allow, shrinking is deliberately slower.
    scale_up_cooldown: float = 5.0
    scale_down_cooldown: float = 30.0
    #: a draining victim gets this long to flush its in-flight work
    #: before the kill goes through anyway.
    drain_timeout: float = 120.0
    #: minimum drain age before the kill: the victim's outstanding
    #: count is heartbeat-lagged, so a just-announced drain must not
    #: read a stale zero and kill mid-request.
    drain_grace: float = 1.0


class FleetAutoscaler:
    """The per-tier feedback loop over a :class:`FleetServer`.

    ``fleet`` must expose the dynamic-fleet surface (``registry``,
    ``metrics``, ``targets``, ``set_target``, ``bounds``,
    ``launch_replica``, ``kill_replica``, ``tier_actual``,
    ``scale_lock``) — tests drive the loop against a stub fleet of
    jax-free replicas through exactly the same surface.
    """

    def __init__(self, fleet, config: Optional[AutoscalerConfig] = None,
                 signals: Optional[Callable[[], Dict[str, Dict[str, Any]]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.fleet = fleet
        self.config = config or AutoscalerConfig()
        self._signals = signals or self._default_signals
        self._clock = clock
        self.log = get_logger("tfmesos_tpu.fleet.autoscaler")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Windowed-percentile state: the previous tick's cumulative
        # queue-wait histogram sample.
        self._prev_queue_wait: Optional[tuple] = None
        # Windowed KV-tier state: the previous tick's fleet-wide
        # counter aggregate, so hit rate is between-ticks (the same
        # react-to-now discipline as the queue-wait window).
        self._prev_kv: Optional[Dict[str, Any]] = None
        self._last_up: Dict[str, float] = {}
        self._last_down: Dict[str, float] = {}
        # addr -> {role, node, since, deadline}: drains in flight.
        self._draining: Dict[str, Dict[str, Any]] = {}
        self._last_action: Dict[str, str] = {}
        if getattr(fleet, "metrics", None) is not None:
            fleet.metrics.register_gauge("autoscaler", self.describe)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetAutoscaler":
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-autoscaler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval):
            try:
                self.step()
            except Exception:
                # One broken tick must not kill the control loop; the
                # fleet keeps serving at its current size either way.
                self.log.exception("autoscaler tick failed")

    # -- signals -----------------------------------------------------------

    def _default_signals(self) -> Dict[str, Dict[str, Any]]:
        """Per-tier signal dict from the live registry + metrics: the
        windowed queue-wait p99 (global — one ingress queue feeds every
        tier), per-tier utilization, and per-tier average KV headroom
        per alive replica."""
        cur = self.fleet.metrics.hist_cumulative("queue_wait_ms")
        qw_p99 = None
        if cur is not None:
            qw_p99 = Histogram.delta_percentile(self._prev_queue_wait,
                                                cur, 0.99)
            self._prev_queue_wait = cur
        # KV-tier occupancy + windowed hit rate (fleet-wide, like the
        # queue-wait window — every tiered replica feeds one session
        # economy).  Counter deltas clamp at zero: a dying replica's
        # counters leave the aggregate, which must not read as
        # negative traffic.
        kv_occ = kv_hit = None
        kvsum = getattr(self.fleet.registry, "kv_tier_summary", None)
        if kvsum is not None:
            cur_kv = kvsum()
            if cur_kv.get("replicas"):
                budget = cur_kv.get("ram_bytes") or 0
                if budget > 0:
                    kv_occ = cur_kv.get("ram_bytes_used", 0) / budget
                prev = self._prev_kv or {}
                hits = max(0, cur_kv.get("hits", 0)
                           - prev.get("hits", 0))
                misses = max(0, cur_kv.get("misses", 0)
                             - prev.get("misses", 0))
                if hits + misses > 0:
                    kv_hit = hits / (hits + misses)
                self._prev_kv = cur_kv
        out: Dict[str, Dict[str, Any]] = {}
        summary = self.fleet.registry.role_summary()
        for role in self.fleet.targets:
            d = summary.get(role, {})
            alive = d.get("alive", 0)
            capacity = sum(r.capacity for r in self.fleet.registry.members(role)
                           if r.state == ALIVE)
            outstanding = d.get("outstanding", 0)
            util = (outstanding / capacity) if capacity > 0 else 0.0
            headroom = (d.get("kv_headroom", 0) / alive) if alive else None
            out[role] = {"queue_wait_p99_ms": qw_p99, "util": util,
                         "kv_headroom": headroom, "alive": alive,
                         "warming": d.get("warming", 0),
                         "kv_occupancy": kv_occ, "kv_hit_rate": kv_hit}
        return out

    # -- the control tick --------------------------------------------------

    def step(self, now: Optional[float] = None) -> None:
        """One control tick: retarget each tier from its signals, then
        converge actuals (launch, drain, reap).  Public so tests (and
        the bench) can drive the loop deterministically."""
        now = self._clock() if now is None else now
        with self.fleet.scale_lock:
            signals = self._signals()
            for role in list(self.fleet.targets):
                self._retarget(role, signals.get(role) or {}, now)
                self._converge(role, now)
            self._reap_drained(now)

    def _members(self, role: str):
        """This tier's registry members.  A fleet exposing
        ``tier_members`` (the model-catalog launcher, the simulator)
        resolves composite ``"<model>/<role>"`` keys there; plain
        fleets keep the direct registry query."""
        tm = getattr(self.fleet, "tier_members", None)
        if tm is not None:
            return tm(role)
        return self.fleet.registry.members(role)

    def _scale_up(self, role: str) -> str:
        """Actuate one tier scale-up; the model trader overrides this
        to prefer warm-pool adoption over a cold launch."""
        return self.fleet.launch_replica(role)

    def _allow_zero(self, role: str) -> bool:
        """Whether this tier may drain its LAST alive replica (the
        scale-to-zero policy); the base loop never does."""
        return False

    def _retarget(self, role: str, sig: Dict[str, Any], now: float) -> None:
        cfg = self.config
        target = self.fleet.targets[role]
        lo, hi = self.fleet.bounds(role)
        # Composite per-(model, tier) keys ("m/decode") resolve their
        # ROLE by suffix — '/' is outside the model-id charset.
        if role.rsplit("/", 1)[-1] == KV:
            # The dedicated KV tier stays pinned at its boot size:
            # storage-only holders produce no queue-wait or
            # utilization signal, so the loop would only ever shrink
            # them — and every shrink throws away parked copies.
            # Convergence (crash relaunch) still runs for the tier.
            return
        if role.rsplit("/", 1)[-1] == DECODE:
            # Decode replicas exhaust KV pages, not rows: headroom is
            # the binding resource.
            headroom = sig.get("kv_headroom")
            util = sig.get("util") or 0.0
            up = headroom is not None and headroom < cfg.kv_headroom_lo
            down = (headroom is not None
                    and headroom > cfg.kv_headroom_hi
                    and util <= cfg.util_lo)
        else:
            qw = sig.get("queue_wait_p99_ms")
            util = sig.get("util") or 0.0
            up = ((qw is not None and qw > cfg.queue_wait_hi_ms)
                  or util > cfg.util_hi)
            down = ((qw is None or qw < cfg.queue_wait_lo_ms)
                    and util < cfg.util_lo)
            # KV-tier pressure (first-class next to queue wait): a
            # saturated tier THRASHING — evicting artifacts the
            # windowed hit rate says traffic still wants — scales up
            # (more replicas = more aggregate tier RAM), and a merely
            # saturated one blocks scale-down (the drained victim's
            # tier capacity would evict more parked sessions).
            kv_occ = sig.get("kv_occupancy")
            kv_hit = sig.get("kv_hit_rate")
            tier_hot = (kv_occ is not None
                        and kv_occ > cfg.kv_tier_occupancy_hi)
            if tier_hot:
                down = False
                if kv_hit is not None \
                        and kv_hit < cfg.kv_tier_hit_rate_lo:
                    up = True
        desired = target
        if up and now - self._last_up.get(role, -1e18) >= cfg.scale_up_cooldown:
            desired = target + 1
        elif (down and not up
              and now - self._last_down.get(role, -1e18)
              >= cfg.scale_down_cooldown):
            desired = target - 1
        # Bounds, and the hard floor: a routable tier never targets 0.
        desired = max(1, max(lo, min(hi, desired)))
        if desired == target:
            return
        direction = "up" if desired > target else "down"
        if direction == "up":
            self._last_up[role] = now
        else:
            self._last_down[role] = now
        self._last_action[role] = f"{direction}:{target}->{desired}"
        self.fleet.set_target(role, desired)
        self.fleet.metrics.inc(f"autoscale_{direction}")
        self.log.info(
            "autoscaler: %s tier target %d -> %d (queue_wait_p99=%s "
            "util=%.2f kv_headroom=%s)", role, target, desired,
            sig.get("queue_wait_p99_ms"), sig.get("util") or 0.0,
            sig.get("kv_headroom"))

    def _converge(self, role: str, now: float) -> None:
        """Drive actual toward target: launch when short (one per tick —
        self-healing of crashed replicas rides this same path), start a
        pinned drain on the least-loaded alive replica when over."""
        target = self.fleet.targets[role]
        pending = [(a, d) for a, d in self._draining.items()
                   if d["role"] == role]
        # Only LIVE draining victims discount "actual": a victim that
        # died mid-drain already left the scheduler's table (the
        # dynamic-death handler removed it), so subtracting its drain
        # record too would undercount the tier and launch a spurious
        # replica — full churn (warmup, then another drain) for
        # nothing.  The pending list itself still gates one-drain-at-
        # a-time below until _reap_drained clears the record.
        members = {r.addr: r for r in self._members(role)}
        live_draining = sum(
            1 for a, _ in pending
            if a in members and members[a].state != DEAD)
        actual = self.fleet.tier_actual(role) - live_draining
        if actual < target:
            node = self._scale_up(role)
            self._last_action[role] = f"launch:{node}"
            self.fleet.metrics.inc("autoscale_launches")
            self.log.info("autoscaler: %s tier %d/%d — launched %s "
                          "(registers warming, routed only once alive)",
                          role, actual, target, node)
            return
        if actual <= target or pending:
            return      # converged, or a drain is already in flight
        alive = [r for r in members.values() if r.state == ALIVE]
        if len(alive) < 2 and not (self._allow_zero(role)
                                   and target < 1):
            # Invariant: never drain a routable tier below one alive
            # replica — even when target says shrink, the LAST alive
            # member waits until its warming replacement (or a peer)
            # is routable.  Scale-to-zero tiers (the model trader's
            # idle models) opt out: their last replica drains away and
            # the next request cold-starts through the warm pool.
            return
        victim = min(alive, key=lambda r: (r.outstanding, r.addr))
        if not self.fleet.registry.begin_drain(victim.addr, pinned=True):
            return
        # Drain-migrate-kill: ask the victim to suspend its in-flight
        # rows so the router re-places them on surviving replicas — the
        # drain flushes promptly and a deadline kill cannot lose work.
        # Best-effort (stub fleets in tests have no migration surface).
        migrate = getattr(self.fleet, "request_migration", None)
        if migrate is not None:
            try:
                migrate(victim.addr)
            except Exception:
                self.log.exception("migrate request to %s failed; its "
                                   "in-flight work drains normally",
                                   victim.addr)
        self._draining[victim.addr] = {
            "role": role, "node": victim.node, "since": now,
            "deadline": now + self.config.drain_timeout}
        self._last_action[role] = f"drain:{victim.addr}"
        self.fleet.metrics.inc("autoscale_drains")
        self.log.info("autoscaler: %s tier %d/%d — draining least-loaded "
                      "%s (outstanding %d; kill after flush)", role,
                      actual, target, victim.addr, victim.outstanding)

    def _reap_drained(self, now: float) -> None:
        """Kill drained victims whose in-flight work has flushed — BOTH
        load signals must read zero: the victim's self-reported
        outstanding (heartbeat-lagged, hence the grace window) and the
        router's own count of requests it still has in flight there (a
        request dispatched right after the victim's last beat is
        invisible to the heartbeat signal) — or whose drain deadline
        passed."""
        router = getattr(self.fleet, "router", None)
        for addr, d in list(self._draining.items()):
            rep = next((r for r in self._members(d["role"])
                        if r.addr == addr), None)
            in_flight = router.outstanding(addr) if router is not None \
                else 0
            flushed = (rep is None or rep.state == DEAD
                       or (rep.outstanding <= 0 and in_flight <= 0
                           and now - d["since"] >= self.config.drain_grace))
            if not flushed and now < d["deadline"]:
                continue
            del self._draining[addr]
            killed = bool(d["node"]) and self.fleet.kill_replica(d["node"])
            if killed or rep is None or rep.state == DEAD:
                self.fleet.metrics.inc("autoscale_kills")
                self._last_action[d["role"]] = f"kill:{addr}"
                self.log.info("autoscaler: reaped drained replica %s "
                              "(%s)", addr,
                              "flushed" if flushed else "drain timeout")
            else:
                # The victim cannot be mapped back to a killable task
                # (no node advertised, or the task vanished): release
                # the pinned drain so its next routable beat revives it
                # — a zombie stuck DRAINING forever would block
                # convergence and get healthy peers drained in its
                # place.
                self.fleet.registry.clear_drain(addr)
                self.fleet.metrics.inc("autoscale_kill_failures")
                self._last_action[d["role"]] = f"kill_failed:{addr}"
                self.log.warning(
                    "autoscaler: cannot kill drained replica %s (node "
                    "%r unknown to the scheduler); drain released",
                    addr, d["node"])

    # -- observability -----------------------------------------------------

    def describe(self) -> Dict[str, Dict[str, Any]]:
        """The ``autoscaler`` gauge: what the loop believes, per tier."""
        out: Dict[str, Dict[str, Any]] = {}
        summary = self.fleet.registry.role_summary()
        for role, target in self.fleet.targets.items():
            d = summary.get(role, {})
            lo, hi = self.fleet.bounds(role)
            out[role] = {
                "target": target,
                "actual": self.fleet.tier_actual(role),
                "alive": d.get("alive", 0),
                "warming": d.get("warming", 0),
                "draining": len([x for x in self._draining.values()
                                 if x["role"] == role]),
                "min": lo, "max": hi,
                "last_action": self._last_action.get(role, ""),
            }
        return out
