"""Fleet observability: thread-safe counters, latency histograms, gauges.

One ``FleetMetrics`` instance is shared by the gateway, router, and
admission controller; everything it exports is a plain-JSON
``snapshot()`` (served over the wire by the gateway's ``metrics`` op and
recorded by ``bench.py`` as the ``fleet_*`` metrics) plus an optional
periodic one-line log report.  No external metrics dependency — the
control plane stays stdlib-only, like the rest of the framework.

Consistency contract (asserted by the end-to-end tests): after the
gateway drains, ``received == admitted + shed_queue + shed_rate_limited``
and ``admitted == completed + failed`` — deadline-carrying traffic adds
``shed_deadline`` (requests shed for an expired end-to-end deadline,
at admission or while queued; the queued ones were admitted and so
count under ``failed`` too) and ``deadline_exceeded`` (deadline errors
relayed from the router/replicas, a subset of ``failed``).

Prefix-affinity routing adds ``affinity_hits``/``affinity_misses``: one
of the two per routing decision over a prompt-bearing request —
``hits / (hits + misses)`` is the fleet's prefix-affinity hit rate
(``fleet_prefix_affinity_hit_rate`` in bench.py).
"""

from __future__ import annotations

import re as _re
import threading
from bisect import bisect_left
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Histogram", "FleetMetrics"]

# Prometheus metric names must match [a-zA-Z_:][a-zA-Z0-9_:]*; our
# counter/histogram names are lowercase identifiers already, but class
# labels are user input ("queue_wait_ms_<class>") — sanitize, never
# trust.
_PROM_BAD = _re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = "fleet_" + _PROM_BAD.sub("_", str(name))
    return out if not out[6:7].isdigit() else "fleet__" + out[6:]


def _prom_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_num(v) -> str:
    f = float(v)
    if f != f:
        # Valid exposition literal — a NaN gauge must cost its sample's
        # accuracy, never the whole scrape (int(nan) would raise here).
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)

# Bucket upper bounds in milliseconds — wide enough for CPU dev replicas
# (seconds) and TPU serving (single-digit ms) alike.
DEFAULT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                      500.0, 1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
                      60000.0, float("inf"))


class Histogram:
    """Fixed-bucket latency histogram; percentiles report the upper edge
    of the bucket the rank falls in (the standard Prometheus-style
    estimate — cheap, monotone, and honest about its resolution)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.buckets = tuple(sorted(buckets))
        if not self.buckets or self.buckets[-1] != float("inf"):
            # Every histogram needs the +inf terminator: the bisect in
            # observe() indexes the bucket for ANY sample, so a
            # caller-supplied bucket list without it would crash the
            # metrics path on the first out-of-range observation.
            self.buckets += (float("inf"),)
        self._counts = [0] * len(self.buckets)
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        if v != v:
            # NaN: it would increment _count while landing in NO bucket
            # (every `v <= edge` comparison is False), silently shifting
            # every percentile's rank — drop it, the same way
            # FleetMetrics.observe drops non-numerics.
            return
        with self._lock:
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            # Buckets are sorted ascending (inf last): binary search
            # for the first edge >= v — this runs several times per
            # served request, so O(log buckets) matters at simulator
            # and fleet scale.
            self._counts[bisect_left(self.buckets, v)] += 1

    def _percentile(self, p: float) -> float:
        # One copy of the rank walk (delta_percentile); the lifetime
        # snapshot substitutes the tracked max for the +inf bucket.
        out = Histogram.delta_percentile(
            None, (self.buckets, tuple(self._counts), self._count), p,
            inf_value=self._max)
        return self._max if out is None else out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self._count:
                return {"count": 0}
            return {
                "count": self._count,
                "mean": round(self._sum / self._count, 3),
                "p50": self._percentile(0.50),
                "p90": self._percentile(0.90),
                "p99": self._percentile(0.99),
                "max": round(self._max, 3),
            }

    def cumulative(self) -> tuple:
        """``(buckets, counts, count)`` — the raw cumulative state, so
        a control loop can diff two samples and compute percentiles
        over JUST the interval between them (Prometheus-style windowed
        p99: a lifetime histogram would never decay and the autoscaler
        would chase load that ended minutes ago)."""
        with self._lock:
            return (self.buckets, tuple(self._counts), self._count)

    def state(self) -> tuple:
        """``(buckets, counts, count, sum)`` — :meth:`cumulative` plus
        the running sum, the full tuple Prometheus exposition needs
        (``cumulative``'s 3-tuple shape is an API the autoscaler
        diffs; this one carries the extra field instead of changing
        it)."""
        with self._lock:
            return (self.buckets, tuple(self._counts), self._count,
                    self._sum)

    def raw(self) -> Dict[str, object]:
        """JSON-safe raw state for cross-process aggregation: bucket
        edges (``None`` stands in for +inf so strict JSON round-trips),
        per-bucket counts, total count/sum, and the tracked max.  The
        inverse of :meth:`merge`."""
        with self._lock:
            return {
                "buckets": [None if e == float("inf") else e
                            for e in self.buckets],
                "counts": list(self._counts),
                "count": self._count,
                "sum": self._sum,
                "max": self._max,
            }

    def merge(self, state: Dict[str, object]) -> None:
        """Fold another histogram's :meth:`raw` state — typically from a
        different gateway process — into this one.  Matching bucket
        layouts add elementwise; a foreign layout re-buckets each count
        at its upper edge (conservative: samples can only move to a
        wider bucket, so merged percentiles never under-report)."""
        edges = tuple(float("inf") if e is None else float(e)
                      for e in state.get("buckets", ()))
        counts = [int(n) for n in state.get("counts", ())]
        with self._lock:
            if edges == self.buckets and len(counts) == len(self._counts):
                for i, n in enumerate(counts):
                    self._counts[i] += n
            else:
                for edge, n in zip(edges, counts):
                    if n:
                        self._counts[bisect_left(self.buckets, edge)] += n
            self._count += int(state.get("count", 0))
            self._sum += float(state.get("sum", 0.0))
            m = state.get("max", 0.0)
            if isinstance(m, (int, float)) and m > self._max:
                self._max = float(m)

    @staticmethod
    def delta_percentile(prev: Optional[tuple], cur: tuple, p: float,
                         inf_value: Optional[float] = None
                         ) -> Optional[float]:
        """Percentile of the samples observed BETWEEN two
        :meth:`cumulative` snapshots (``prev`` may be ``None`` for
        since-birth); ``None`` when the window holds no samples.  A
        rank landing in the +inf bucket reports ``inf_value`` when the
        caller tracks a true max (the lifetime snapshot), else the
        last finite bucket edge."""
        buckets, counts, total = cur
        if prev is not None:
            pbuckets, pcounts, ptotal = prev
            if pbuckets == buckets:
                counts = tuple(c - q for c, q in zip(counts, pcounts))
                total = total - ptotal
        if total <= 0:
            return None
        rank = p * total
        seen = 0
        last_finite = 0.0
        for edge, n in zip(buckets, counts):
            seen += n
            if edge != float("inf"):
                last_finite = edge
            if seen >= rank:
                if edge == float("inf"):
                    break
                return edge
        return last_finite if inf_value is None else inf_value


class FleetMetrics:
    """Named counters + histograms + pull-style gauges with one JSON
    ``snapshot()`` and an optional periodic log line."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, Histogram] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._gauge_acc: Dict[str, float] = {}
        #: optional scrape-time fan-in (docs/SERVING.md "Multi-process
        #: gateways"): a callable returning the OTHER processes'
        #: :meth:`raw_state` dicts.  When set, the HTTP exporter serves
        #: the fleet-level merge instead of this process alone.
        self.fanin: Optional[Callable[[], List[dict]]] = None
        self._reporter: Optional[threading.Thread] = None
        self._reporter_stop = threading.Event()

    # -- counters ----------------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    # -- histograms --------------------------------------------------------

    def observe(self, name: str, value) -> None:
        """Record one latency sample; non-numeric values are dropped (a
        replica may omit a timing field rather than lie about it)."""
        if not isinstance(value, (int, float)):
            return
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram()
        hist.observe(value)

    def hist(self, name: str) -> Histogram:
        """The named histogram itself (created on first use) — hot
        paths that observe the same series per request hold this
        handle instead of paying the registry lock + lookup each
        time."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
        return h

    def hist_cumulative(self, name: str) -> Optional[tuple]:
        """The named histogram's :meth:`Histogram.cumulative` state, or
        ``None`` before its first observation — the autoscaler samples
        this per tick to compute windowed percentiles."""
        with self._lock:
            hist = self._hists.get(name)
        return hist.cumulative() if hist is not None else None

    def percentile(self, name: str, p: float) -> Optional[float]:
        """Lifetime percentile of one histogram (``None`` when it has
        no samples yet)."""
        cur = self.hist_cumulative(name)
        if cur is None:
            return None
        return Histogram.delta_percentile(None, cur, p)

    # -- gauges ------------------------------------------------------------

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """``fn`` is sampled at snapshot time (queue depth, replicas
        alive, ...); it must be cheap and never raise."""
        with self._lock:
            self._gauges[name] = fn

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            counters = dict(self._counters)
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        out = {
            "counters": counters,
            "gauges": {},
            "histograms": {name: h.snapshot() for name, h in hists.items()},
        }
        for name, fn in gauges.items():
            try:
                out["gauges"][name] = fn()
            except Exception:  # pragma: no cover - gauge must not break export
                out["gauges"][name] = None
        return out

    def raw_state(self) -> Dict[str, dict]:
        """Mergeable raw export: counters, sampled gauge values, and
        per-histogram :meth:`Histogram.raw` states.  This is what a
        gateway process ships over the wire (``metrics`` op with
        ``raw: true``) so the launcher-side scrape can fan N processes
        into one registry via :meth:`merge_raw` — ``snapshot()`` only
        carries percentile estimates, which cannot be aggregated."""
        with self._lock:
            counters = dict(self._counters)
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        out: Dict[str, dict] = {"counters": counters, "gauges": {},
                                "histograms": {}}
        for name, fn in gauges.items():
            try:
                out["gauges"][name] = fn()
            except Exception:  # pragma: no cover - gauge must not break export
                out["gauges"][name] = None
        for name, h in hists.items():
            out["histograms"][name] = h.raw()
        return out

    def merge_raw(self, raw: Dict[str, dict]) -> None:
        """Fold one process's :meth:`raw_state` into this registry:
        counters add, histograms bucket-merge, and numeric gauges
        accumulate as SUMS across every merge (right for queue depths
        and inflight counts; per-process identity gauges like bound
        ports belong in the per-process scrape, not the fan-in)."""
        for name, n in (raw.get("counters") or {}).items():
            try:
                self.inc(name, int(n))
            except (TypeError, ValueError):
                continue
        for name, st in (raw.get("histograms") or {}).items():
            if isinstance(st, dict):
                self.hist(name).merge(st)
        for name, val in (raw.get("gauges") or {}).items():
            if isinstance(val, bool) or not isinstance(val, (int, float)):
                continue
            with self._lock:
                self._gauge_acc[name] = self._gauge_acc.get(name, 0) + val
            self.register_gauge(
                name, lambda n=name: self._gauge_acc.get(n, 0))

    def merged(self) -> "FleetMetrics":
        """One fleet-level registry: this process's own raw state folded
        with whatever :attr:`fanin` returns (each entry a peer
        process's :meth:`raw_state`).  A peer that fails to scrape
        costs its contribution, never the merge."""
        out = FleetMetrics()
        out.merge_raw(self.raw_state())
        raws: List[dict] = []
        if self.fanin is not None:
            try:
                raws = list(self.fanin() or [])
            except Exception:  # pragma: no cover - scrape must not break export
                raws = []
        for raw in raws:
            if isinstance(raw, dict):
                out.merge_raw(raw)
        return out

    def prometheus_text(self) -> str:
        """The whole metrics surface in Prometheus exposition format
        (text/plain version 0.0.4): counters and numeric gauges as-is,
        dict-valued gauges flattened one level into ``{key="..."}``
        labels (numeric leaves only), histograms as CUMULATIVE
        ``_bucket{le="..."}`` series plus ``_sum``/``_count`` — served
        by the optional stdlib HTTP exporter (``tfserve
        --metrics-port``).  Names are prefixed ``fleet_`` and
        sanitized; a raising gauge costs its series, never the
        scrape."""
        with self._lock:
            counters = dict(self._counters)
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        lines: List[str] = []

        def emit(name: str, kind: str, samples) -> None:
            lines.append(f"# TYPE {name} {kind}")
            for labels, value in samples:
                lines.append(f"{name}{labels} {_prom_num(value)}")

        for name in sorted(counters):
            emit(_prom_name(name) + "_total", "counter",
                 [("", counters[name])])
        for name in sorted(gauges):
            try:
                val = gauges[name]()
            except Exception:   # pragma: no cover - gauge must not break
                continue
            gname = _prom_name(name)
            if isinstance(val, bool):
                continue
            if isinstance(val, (int, float)):
                emit(gname, "gauge", [("", val)])
            elif isinstance(val, dict):
                samples = [(f'{{key="{_prom_label(k)}"}}', v)
                           for k, v in sorted(val.items())
                           if isinstance(v, (int, float))
                           and not isinstance(v, bool)]
                if samples:
                    emit(gname, "gauge", samples)
        for name in sorted(hists):
            buckets, counts, count, total = hists[name].state()
            hname = _prom_name(name)
            lines.append(f"# TYPE {hname} histogram")
            seen = 0
            for edge, n in zip(buckets, counts):
                seen += n
                le = "+Inf" if edge == float("inf") else _prom_num(edge)
                lines.append(f'{hname}_bucket{{le="{le}"}} {seen}')
            lines.append(f"{hname}_sum {_prom_num(total)}")
            lines.append(f"{hname}_count {count}")
        return "\n".join(lines) + "\n"

    def start_http_server(self, port: int, host: str = "127.0.0.1"):
        """Serve ``GET /metrics`` (Prometheus text) and ``GET
        /metrics.json`` (the snapshot) on a daemon thread — stdlib
        ``http.server`` only, like the rest of the control plane.
        Returns the server; call its ``shutdown()`` to stop.  Metrics
        are operational telemetry, not completions, so this read-only
        endpoint is unauthenticated by design — bind it to loopback
        (the default) or a scrape-only network."""
        import http.server
        import json

        metrics = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):         # noqa: N802 - stdlib casing
                # Fan-in happens at scrape time: with `fanin` set this
                # endpoint serves the fleet-level merge of every
                # gateway process, not this process alone.
                src = metrics.merged() if metrics.fanin is not None \
                    else metrics
                if self.path.split("?")[0] == "/metrics.json":
                    body = json.dumps(src.snapshot()).encode()
                    ctype = "application/json"
                elif self.path.split("?")[0] in ("/", "/metrics"):
                    body = src.prometheus_text().encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args) -> None:
                pass    # scrapes are not log events

        try:
            server = http.server.ThreadingHTTPServer((host, int(port)),
                                                     Handler)
        except OSError:
            if not port:
                raise
            # The requested port is taken — with N gateway processes on
            # one host only the first wins a fixed --metrics-port, and
            # silently dying here would leave N-1 processes unscraped.
            # Fall back to an OS-assigned port; the `metrics_http_port`
            # gauge below tells scrapers (and `tfserve metrics`) where
            # this process actually landed.
            server = http.server.ThreadingHTTPServer((host, 0), Handler)
        server.daemon_threads = True
        bound_port = int(server.server_address[1])
        self.register_gauge("metrics_http_port", lambda p=bound_port: p)
        t = threading.Thread(target=server.serve_forever,
                             name="fleet-metrics-http", daemon=True)
        t.start()
        return server

    def report_line(self) -> str:
        """One log-friendly line: every counter and gauge, plus the
        headline latency numbers."""
        snap = self.snapshot()
        parts: List[str] = []
        for name in sorted(snap["counters"]):
            parts.append(f"{name}={snap['counters'][name]}")
        for name in sorted(snap["gauges"]):
            parts.append(f"{name}={snap['gauges'][name]}")
        for name in sorted(snap["histograms"]):
            h = snap["histograms"][name]
            if h.get("count"):
                # p50 AND p99: the autoscaler keys off tail latency, so
                # the tail must be a first-class observable, not a
                # median that hides the very stalls scaling reacts to.
                parts.append(f"{name}_p50={h['p50']}")
                parts.append(f"{name}_p99={h['p99']}")
        return "fleet: " + " ".join(parts)

    def start_reporter(self, log, interval: float = 10.0) -> None:
        """Log ``report_line()`` every ``interval`` seconds until
        :meth:`stop_reporter` (daemon thread; idempotent)."""
        if self._reporter is not None:
            return
        self._reporter_stop.clear()

        def loop() -> None:
            while not self._reporter_stop.wait(interval):
                log.info("%s", self.report_line())

        self._reporter = threading.Thread(target=loop, name="fleet-metrics",
                                          daemon=True)
        self._reporter.start()

    def stop_reporter(self) -> None:
        if self._reporter is None:
            return
        self._reporter_stop.set()
        self._reporter.join(timeout=2.0)
        self._reporter = None
