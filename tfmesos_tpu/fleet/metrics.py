"""Fleet observability: thread-safe counters, latency histograms, gauges.

One ``FleetMetrics`` instance is shared by the gateway, router, and
admission controller; everything it exports is a plain-JSON
``snapshot()`` (served over the wire by the gateway's ``metrics`` op and
recorded by ``bench.py`` as the ``fleet_*`` metrics) plus an optional
periodic one-line log report.  No external metrics dependency — the
control plane stays stdlib-only, like the rest of the framework.

Consistency contract (asserted by the end-to-end tests): after the
gateway drains, ``received == admitted + shed_queue + shed_rate_limited``
and ``admitted == completed + failed`` — deadline-carrying traffic adds
``shed_deadline`` (requests shed for an expired end-to-end deadline,
at admission or while queued; the queued ones were admitted and so
count under ``failed`` too) and ``deadline_exceeded`` (deadline errors
relayed from the router/replicas, a subset of ``failed``).

Prefix-affinity routing adds ``affinity_hits``/``affinity_misses``: one
of the two per routing decision over a prompt-bearing request —
``hits / (hits + misses)`` is the fleet's prefix-affinity hit rate
(``fleet_prefix_affinity_hit_rate`` in bench.py).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Histogram", "FleetMetrics"]

# Bucket upper bounds in milliseconds — wide enough for CPU dev replicas
# (seconds) and TPU serving (single-digit ms) alike.
DEFAULT_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                      500.0, 1000.0, 2000.0, 5000.0, 10000.0, 30000.0,
                      60000.0, float("inf"))


class Histogram:
    """Fixed-bucket latency histogram; percentiles report the upper edge
    of the bucket the rank falls in (the standard Prometheus-style
    estimate — cheap, monotone, and honest about its resolution)."""

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.buckets = tuple(buckets)
        self._counts = [0] * len(self.buckets)
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if v > self._max:
                self._max = v
            for i, edge in enumerate(self.buckets):
                if v <= edge:
                    self._counts[i] += 1
                    break

    def _percentile(self, p: float) -> float:
        # One copy of the rank walk (delta_percentile); the lifetime
        # snapshot substitutes the tracked max for the +inf bucket.
        out = Histogram.delta_percentile(
            None, (self.buckets, tuple(self._counts), self._count), p,
            inf_value=self._max)
        return self._max if out is None else out

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if not self._count:
                return {"count": 0}
            return {
                "count": self._count,
                "mean": round(self._sum / self._count, 3),
                "p50": self._percentile(0.50),
                "p90": self._percentile(0.90),
                "p99": self._percentile(0.99),
                "max": round(self._max, 3),
            }

    def cumulative(self) -> tuple:
        """``(buckets, counts, count)`` — the raw cumulative state, so
        a control loop can diff two samples and compute percentiles
        over JUST the interval between them (Prometheus-style windowed
        p99: a lifetime histogram would never decay and the autoscaler
        would chase load that ended minutes ago)."""
        with self._lock:
            return (self.buckets, tuple(self._counts), self._count)

    @staticmethod
    def delta_percentile(prev: Optional[tuple], cur: tuple, p: float,
                         inf_value: Optional[float] = None
                         ) -> Optional[float]:
        """Percentile of the samples observed BETWEEN two
        :meth:`cumulative` snapshots (``prev`` may be ``None`` for
        since-birth); ``None`` when the window holds no samples.  A
        rank landing in the +inf bucket reports ``inf_value`` when the
        caller tracks a true max (the lifetime snapshot), else the
        last finite bucket edge."""
        buckets, counts, total = cur
        if prev is not None:
            pbuckets, pcounts, ptotal = prev
            if pbuckets == buckets:
                counts = tuple(c - q for c, q in zip(counts, pcounts))
                total = total - ptotal
        if total <= 0:
            return None
        rank = p * total
        seen = 0
        last_finite = 0.0
        for edge, n in zip(buckets, counts):
            seen += n
            if edge != float("inf"):
                last_finite = edge
            if seen >= rank:
                if edge == float("inf"):
                    break
                return edge
        return last_finite if inf_value is None else inf_value


class FleetMetrics:
    """Named counters + histograms + pull-style gauges with one JSON
    ``snapshot()`` and an optional periodic log line."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, Histogram] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._reporter: Optional[threading.Thread] = None
        self._reporter_stop = threading.Event()

    # -- counters ----------------------------------------------------------

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    # -- histograms --------------------------------------------------------

    def observe(self, name: str, value) -> None:
        """Record one latency sample; non-numeric values are dropped (a
        replica may omit a timing field rather than lie about it)."""
        if not isinstance(value, (int, float)):
            return
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram()
        hist.observe(value)

    def hist_cumulative(self, name: str) -> Optional[tuple]:
        """The named histogram's :meth:`Histogram.cumulative` state, or
        ``None`` before its first observation — the autoscaler samples
        this per tick to compute windowed percentiles."""
        with self._lock:
            hist = self._hists.get(name)
        return hist.cumulative() if hist is not None else None

    def percentile(self, name: str, p: float) -> Optional[float]:
        """Lifetime percentile of one histogram (``None`` when it has
        no samples yet)."""
        cur = self.hist_cumulative(name)
        if cur is None:
            return None
        return Histogram.delta_percentile(None, cur, p)

    # -- gauges ------------------------------------------------------------

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        """``fn`` is sampled at snapshot time (queue depth, replicas
        alive, ...); it must be cheap and never raise."""
        with self._lock:
            self._gauges[name] = fn

    # -- export ------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            counters = dict(self._counters)
            hists = dict(self._hists)
            gauges = dict(self._gauges)
        out = {
            "counters": counters,
            "gauges": {},
            "histograms": {name: h.snapshot() for name, h in hists.items()},
        }
        for name, fn in gauges.items():
            try:
                out["gauges"][name] = fn()
            except Exception:  # pragma: no cover - gauge must not break export
                out["gauges"][name] = None
        return out

    def report_line(self) -> str:
        """One log-friendly line: every counter and gauge, plus the
        headline latency numbers."""
        snap = self.snapshot()
        parts: List[str] = []
        for name in sorted(snap["counters"]):
            parts.append(f"{name}={snap['counters'][name]}")
        for name in sorted(snap["gauges"]):
            parts.append(f"{name}={snap['gauges'][name]}")
        for name in sorted(snap["histograms"]):
            h = snap["histograms"][name]
            if h.get("count"):
                # p50 AND p99: the autoscaler keys off tail latency, so
                # the tail must be a first-class observable, not a
                # median that hides the very stalls scaling reacts to.
                parts.append(f"{name}_p50={h['p50']}")
                parts.append(f"{name}_p99={h['p99']}")
        return "fleet: " + " ".join(parts)

    def start_reporter(self, log, interval: float = 10.0) -> None:
        """Log ``report_line()`` every ``interval`` seconds until
        :meth:`stop_reporter` (daemon thread; idempotent)."""
        if self._reporter is not None:
            return
        self._reporter_stop.clear()

        def loop() -> None:
            while not self._reporter_stop.wait(interval):
                log.info("%s", self.report_line())

        self._reporter = threading.Thread(target=loop, name="fleet-metrics",
                                          daemon=True)
        self._reporter.start()

    def stop_reporter(self) -> None:
        if self._reporter is None:
            return
        self._reporter_stop.set()
        self._reporter.join(timeout=2.0)
        self._reporter = None
