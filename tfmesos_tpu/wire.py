"""Authenticated control-plane wire protocol.

The reference framework (tfmesos/utils.py:6-15) frames messages as a 4-byte
big-endian length followed by a *pickle* payload, unauthenticated.  That design
is reproduced here in shape only: we keep the simple length-prefixed framing
(so the control plane stays a handful of syscalls per message) but replace the
encoding with JSON and add an HMAC-SHA256 tag keyed by a per-cluster token, so
a task can only join the rendezvous if it was launched by our scheduler.

Frame layout::

    +----------------+----------------------+------------------+
    | 4B len (BE)    | 32B HMAC-SHA256 tag  | JSON body (UTF8) |
    +----------------+----------------------+------------------+

``len`` counts tag + body.  When ``token`` is empty the tag is still present
but computed with the empty key, keeping the frame layout static.

RAW frames (disaggregated serving's KV-page transfer) carry multi-MB
tensor payloads that must not round-trip through a text encoding: the
length prefix's TOP BIT marks the frame raw (JSON frames cap at
``MAX_FRAME`` = 64 MiB, so the bit is never set on one — old receivers
reject a raw frame loudly as oversized instead of mis-framing), and the
payload is::

    +----------------------+--------------+-----------+------+
    | 32B HMAC-SHA256 tag  | 4B meta len  | JSON meta | body |
    +----------------------+--------------+-----------+------+

decoded to a :class:`RawFrame`.  The tag covers everything after it
(meta length + meta + body) and is verified BEFORE the metadata is
decoded.  The meta header is JSON on purpose: a pickle header would
hand arbitrary code execution to any token holder (serve clients get
the token), where JSON caps the blast radius at request injection —
the same trust boundary every JSON frame already grants.  The body is
never copied through an encoder: ``send_raw_msg`` writes the caller's
buffer straight to the socket.  Raw DECODING is opt-in per stream
(``Framer(allow_raw=True)`` / ``recv_msg(allow_raw=True)``): only
links that legitimately carry KV payloads widen their pre-auth
buffering bound from ``MAX_FRAME`` (64 MiB) to ``MAX_RAW_FRAME``
(1 GiB); every other listener rejects the raw bit at the 4-byte
length prefix.
"""

from __future__ import annotations

import hmac
import hashlib
import json
import os
import socket
import struct
from typing import Any, List, Optional

_LEN = struct.Struct(">I")
TAG_SIZE = hashlib.sha256().digest_size  # 32
MAX_FRAME = 64 * 1024 * 1024  # sanity bound; control messages are tiny
# Raw (binary) frames: top bit of the length prefix set; bound sized for
# KV-page payloads (whole paged pools are O(100 MB) at serving scale).
RAW_FLAG = 0x80000000
MAX_RAW_FRAME = 1 << 30  # 1 GiB
MAX_RAW_META = 1 << 20   # JSON metadata is a small dict

TOKEN_ENV = "TPUMESOS_TOKEN"
TOKEN_FILE_ENV = "TPUMESOS_TOKEN_FILE"


class WireError(Exception):
    """Malformed, oversized, or unauthenticated frame."""


class RawFrame:
    """A decoded raw binary frame: small ``meta`` (any JSON-encodable
    object, in practice a dict with ``op``/``id`` like the JSON
    messages) plus a zero-copy ``body`` (bytes).  Sent with :func:`send_raw_msg`;
    an ``allow_raw`` ``recv_msg``/``Framer`` yields one wherever a JSON
    message could appear, so both kinds interleave on one
    authenticated stream."""

    __slots__ = ("meta", "body")

    def __init__(self, meta: Any, body: bytes):
        self.meta = meta
        self.body = body

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"RawFrame(meta={self.meta!r}, body=<{len(self.body)}B>)"


# Fault-injection hooks (chaos.FaultPlan.install): consulted per framed
# send/recv when set, so tests can sever/delay/truncate/drop traffic on a
# live connection deterministically.  ``None`` (the default) costs one
# attribute load per message.
_chaos_send = None      # Optional[Callable[[socket, bytes], bool]]
_chaos_recv = None      # Optional[Callable[[socket], None]]


def set_chaos(send=None, recv=None) -> None:
    """Install (or clear, with Nones) the process-global wire fault hooks.

    ``send(sock, frame) -> bool`` runs before every ``send_msg`` frame
    hits the socket — it may sleep (delay), raise OSError after closing
    the socket (sever), write a partial frame then raise (truncate), or
    return True to silently swallow the frame (drop).  ``recv(sock)``
    runs before every blocking ``recv_msg`` and may sleep or sever.
    """
    global _chaos_send, _chaos_recv
    _chaos_send = send
    _chaos_recv = recv


def new_token() -> str:
    """Fresh per-cluster auth token (scheduler generates one per bring-up)."""
    return os.urandom(16).hex()


def load_token(environ=os.environ) -> str:
    """Resolve the cluster token a task was launched with.

    Prefers a mode-0600 token *file* (``TPUMESOS_TOKEN_FILE``) over the plain
    env var: env vars leak through Mesos state endpoints and /proc environ,
    so co-located backends deliver the secret out-of-band (advisor finding on
    spec.py token delivery).
    """
    path = environ.get(TOKEN_FILE_ENV)
    if path:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    return environ.get(TOKEN_ENV, "")


def _tag(token: str, body: bytes) -> bytes:
    return hmac.new(token.encode("utf-8"), body, hashlib.sha256).digest()


def encode(obj: Any, token: str = "") -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    tag = _tag(token, body)
    return _LEN.pack(TAG_SIZE + len(body)) + tag + body


def _decode_body(payload: bytes, token: str) -> Any:
    if len(payload) < TAG_SIZE:
        raise WireError("frame shorter than auth tag")
    tag, body = payload[:TAG_SIZE], payload[TAG_SIZE:]
    if not hmac.compare_digest(tag, _tag(token, body)):
        raise WireError("bad auth tag")
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad JSON body: {e}") from e


def send_msg(sock: socket.socket, obj: Any, token: str = "") -> None:
    data = encode(obj, token)
    hook = _chaos_send    # snapshot: a concurrent uninstall must not
    if hook is not None and hook(sock, data):   # turn this into a None call
        return      # frame consumed (chaos drop)
    sock.sendall(data)


def _decode_raw(payload: bytes, token: str) -> RawFrame:
    if len(payload) < TAG_SIZE + _LEN.size:
        raise WireError("raw frame shorter than tag + meta length")
    tag, rest = payload[:TAG_SIZE], memoryview(payload)[TAG_SIZE:]
    if not hmac.compare_digest(tag, _tag(token, rest)):
        raise WireError("bad auth tag on raw frame")
    (meta_len,) = _LEN.unpack(rest[:_LEN.size])
    if meta_len > MAX_RAW_META or _LEN.size + meta_len > len(rest):
        raise WireError(f"bad raw meta length {meta_len}")
    # JSON, never pickle: an authenticated peer must not gain code
    # execution from a crafted meta header (clients hold the token too).
    try:
        meta = json.loads(
            bytes(rest[_LEN.size:_LEN.size + meta_len]).decode("utf-8"))
    except Exception as e:
        raise WireError(f"bad raw meta: {e!r}") from e
    return RawFrame(meta, bytes(rest[_LEN.size + meta_len:]))


def encode_raw(meta: Any, body, token: str = "") -> bytes:
    """One raw frame as contiguous bytes (tests / chaos hooks; the hot
    path is :func:`send_raw_msg`, which never concatenates the body)."""
    header, mv = _raw_parts(meta, body, token)
    return header + bytes(mv)


def _raw_parts(meta: Any, body, token: str):
    """(header bytes, body memoryview) for one raw frame."""
    meta_b = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    if len(meta_b) > MAX_RAW_META:
        raise WireError(f"raw meta of {len(meta_b)} bytes exceeds limit")
    mv = memoryview(body).cast("B") if not isinstance(body, bytes) \
        else memoryview(body)
    length = TAG_SIZE + _LEN.size + len(meta_b) + len(mv)
    if length > MAX_RAW_FRAME:
        raise WireError(f"raw frame of {length} bytes exceeds limit")
    ml = _LEN.pack(len(meta_b))
    mac = hmac.new(token.encode("utf-8"), ml, hashlib.sha256)
    mac.update(meta_b)
    mac.update(mv)
    header = _LEN.pack(RAW_FLAG | length) + mac.digest() + ml + meta_b
    return header, mv


def send_raw_msg(sock: socket.socket, meta: Any, body,
                 token: str = "") -> None:
    """Send one raw frame: ``meta`` (JSON-encodable header) + ``body`` (bytes
    or any buffer), HMAC-tagged like every other frame.  The body goes
    to the socket straight from the caller's buffer — no text encoding
    or concatenation of multi-MB payloads."""
    header, mv = _raw_parts(meta, body, token)
    hook = _chaos_send    # snapshot against a concurrent uninstall
    if hook is not None:
        data = header + bytes(mv)   # chaos-only path; copies are fine
        if hook(sock, data):
            return
        sock.sendall(data)
        return
    sock.sendall(header)
    sock.sendall(mv)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise WireError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, token: str = "",
             allow_raw: bool = False) -> Any:
    """Next message: a decoded JSON object, or (with ``allow_raw``) a
    :class:`RawFrame`.  Raw framing is OPT-IN per stream: an
    unauthenticated peer could otherwise claim a ``MAX_RAW_FRAME``
    (1 GiB) length and force that much buffering before the tag check,
    so listeners that never expect KV payloads keep the 64 MiB
    pre-auth bound and reject the raw bit outright."""
    hook = _chaos_recv    # snapshot against a concurrent uninstall
    if hook is not None:
        hook(sock)
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length & RAW_FLAG:
        if not allow_raw:
            raise WireError("raw frame not accepted on this stream")
        length &= ~RAW_FLAG
        if length > MAX_RAW_FRAME:
            raise WireError(f"raw frame of {length} bytes exceeds limit")
        return _decode_raw(_recv_exact(sock, length), token)
    if length > MAX_FRAME:
        raise WireError(f"frame of {length} bytes exceeds limit")
    return _decode_body(_recv_exact(sock, length), token)


class Framer:
    """Incremental decoder for non-blocking / selector-driven loops.

    The scheduler's rendezvous loop (the analogue of the reference's 0.1s
    select poll, scheduler.py:341-361, but event-driven) feeds raw bytes in
    and pulls complete decoded messages out.
    """

    def __init__(self, token: str = "", allow_raw: bool = False) -> None:
        self._token = token
        self._buf = bytearray()
        # Raw framing is opt-in per stream (see recv_msg): only links
        # that legitimately carry KV payloads (replica servers, mux
        # connections) widen their pre-auth buffering bound to
        # MAX_RAW_FRAME; everything else rejects the raw bit at the
        # 4-byte prefix, before any body buffers.
        self._allow_raw = allow_raw

    def feed(self, data: bytes) -> List[Any]:
        self._buf.extend(data)
        out = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (length,) = _LEN.unpack(bytes(self._buf[: _LEN.size]))
            raw = bool(length & RAW_FLAG)
            if raw:
                if not self._allow_raw:
                    raise WireError("raw frame not accepted on this "
                                    "stream")
                length &= ~RAW_FLAG
                if length > MAX_RAW_FRAME:
                    raise WireError(f"raw frame of {length} bytes "
                                    f"exceeds limit")
            elif length > MAX_FRAME:
                raise WireError(f"frame of {length} bytes exceeds limit")
            end = _LEN.size + length
            if len(self._buf) < end:
                break
            payload = bytes(self._buf[_LEN.size : end])
            del self._buf[:end]
            out.append(_decode_raw(payload, self._token) if raw
                       else _decode_body(payload, self._token))
        return out


def iter_msgs(sock: socket.socket, framer: "Framer"):
    """Decoded messages from a blocking socket, until EOF ends the
    generator — the shared read loop of every long-lived control
    connection (fleet gateway/replica/registry/mux client).  A bad
    frame raises :class:`WireError`; socket errors propagate.

    Consults the chaos recv hook per blocking read, like
    :func:`recv_msg` — so fault plans can sever/delay the fleet's
    long-lived links (mux connections, heartbeat streams, the
    drain-migration KV handoff) mid-stream, not just the scheduler's
    one-shot recv paths."""
    while True:
        hook = _chaos_recv      # snapshot against a concurrent uninstall
        if hook is not None:
            hook(sock)
        data = sock.recv(65536)
        if not data:
            return
        for msg in framer.feed(data):
            yield msg


def connect(addr: str, timeout: Optional[float] = 30.0) -> socket.socket:
    """Dial a ``host:port`` string (the form used throughout the control plane)."""
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(timeout)
    return sock


def bind_ephemeral(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """Bind a listening socket on an OS-assigned port (reference pattern at
    scheduler.py:325-328 / server.py:18-21).  ``port`` pins a specific
    port instead (the fleet gateway's stable front-door address)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def wake_listener(sock: Optional[socket.socket]) -> None:
    """Wake a thread blocked in ``accept()`` on this listening socket.

    ``close()`` alone does NOT interrupt a blocked ``accept`` on Linux —
    the syscall stays parked until the next real connection, so every
    ``stop()`` that merely closed its listener used to burn the full
    thread-join timeout (2-5s per component; whole seconds of pure
    teardown per fleet test).  A throwaway self-connection makes the
    accept return; the loop re-checks its stop flag and exits.  Call
    AFTER setting the stop flag and BEFORE closing the socket."""
    if sock is None:
        return
    try:
        host, port = sock.getsockname()[:2]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        elif host == "::":
            host = "::1"
        poke = socket.create_connection((host, port), timeout=0.5)
        poke.close()
    except OSError:
        pass


def sock_addr(sock: socket.socket, advertise_host: Optional[str] = None) -> str:
    host, port = sock.getsockname()[:2]
    if advertise_host:
        host = advertise_host
    elif host in ("0.0.0.0", "::"):
        host = socket.gethostbyname(socket.gethostname())
    return f"{host}:{port}"
