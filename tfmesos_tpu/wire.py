"""Authenticated control-plane wire protocol.

The reference framework (tfmesos/utils.py:6-15) frames messages as a 4-byte
big-endian length followed by a *pickle* payload, unauthenticated.  That design
is reproduced here in shape only: we keep the simple length-prefixed framing
(so the control plane stays a handful of syscalls per message) but replace the
encoding with JSON and add an HMAC-SHA256 tag keyed by a per-cluster token, so
a task can only join the rendezvous if it was launched by our scheduler.

Frame layout::

    +----------------+----------------------+------------------+
    | 4B len (BE)    | 32B HMAC-SHA256 tag  | JSON body (UTF8) |
    +----------------+----------------------+------------------+

``len`` counts tag + body.  When ``token`` is empty the tag is still present
but computed with the empty key, keeping the frame layout static.

RAW frames (disaggregated serving's KV-page transfer) carry multi-MB
tensor payloads that must not round-trip through a text encoding: the
length prefix's TOP BIT marks the frame raw (JSON frames cap at
``MAX_FRAME`` = 64 MiB, so the bit is never set on one — old receivers
reject a raw frame loudly as oversized instead of mis-framing), and the
payload is::

    +----------------------+--------------+-----------+------+
    | 32B HMAC-SHA256 tag  | 4B meta len  | JSON meta | body |
    +----------------------+--------------+-----------+------+

decoded to a :class:`RawFrame`.  The tag covers everything after it
(meta length + meta + body) and is verified BEFORE the metadata is
decoded.  The meta header is JSON on purpose: a pickle header would
hand arbitrary code execution to any token holder (serve clients get
the token), where JSON caps the blast radius at request injection —
the same trust boundary every JSON frame already grants.  The body is
never copied through an encoder: ``send_raw_msg`` writes the caller's
buffer straight to the socket.  Raw DECODING is opt-in per stream
(``Framer(allow_raw=True)`` / ``recv_msg(allow_raw=True)``): only
links that legitimately carry KV payloads widen their pre-auth
buffering bound from ``MAX_FRAME`` (64 MiB) to ``MAX_RAW_FRAME``
(1 GiB); every other listener rejects the raw bit at the 4-byte
length prefix.
"""

from __future__ import annotations

import hmac
import hashlib
import json
import os
import selectors
import socket
import struct
import threading
import time
import weakref
from typing import Any, Callable, List, Optional

_LEN = struct.Struct(">I")
TAG_SIZE = hashlib.sha256().digest_size  # 32
MAX_FRAME = 64 * 1024 * 1024  # sanity bound; control messages are tiny
# Raw (binary) frames: top bit of the length prefix set; bound sized for
# KV-page payloads (whole paged pools are O(100 MB) at serving scale).
RAW_FLAG = 0x80000000
MAX_RAW_FRAME = 1 << 30  # 1 GiB
MAX_RAW_META = 1 << 20   # JSON metadata is a small dict

TOKEN_ENV = "TPUMESOS_TOKEN"
TOKEN_FILE_ENV = "TPUMESOS_TOKEN_FILE"


class WireError(Exception):
    """Malformed, oversized, or unauthenticated frame."""


class RawFrame:
    """A decoded raw binary frame: small ``meta`` (any JSON-encodable
    object, in practice a dict with ``op``/``id`` like the JSON
    messages) plus a zero-copy ``body`` (bytes).  Sent with :func:`send_raw_msg`;
    an ``allow_raw`` ``recv_msg``/``Framer`` yields one wherever a JSON
    message could appear, so both kinds interleave on one
    authenticated stream."""

    __slots__ = ("meta", "body")

    def __init__(self, meta: Any, body: bytes):
        self.meta = meta
        self.body = body

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"RawFrame(meta={self.meta!r}, body=<{len(self.body)}B>)"


# Fault-injection hooks (chaos.FaultPlan.install): consulted per framed
# send/recv when set, so tests can sever/delay/truncate/drop traffic on a
# live connection deterministically.  ``None`` (the default) costs one
# attribute load per message.
_chaos_send = None      # Optional[Callable[[socket, bytes], bool]]
_chaos_recv = None      # Optional[Callable[[socket], None]]


def set_chaos(send=None, recv=None) -> None:
    """Install (or clear, with Nones) the process-global wire fault hooks.

    ``send(sock, frame) -> bool`` runs before every ``send_msg`` frame
    hits the socket — it may sleep (delay), raise OSError after closing
    the socket (sever), write a partial frame then raise (truncate), or
    return True to silently swallow the frame (drop).  ``recv(sock)``
    runs before every blocking ``recv_msg`` and may sleep or sever.
    """
    global _chaos_send, _chaos_recv
    _chaos_send = send
    _chaos_recv = recv


# Socket identity tags (chaos ``partition`` faults): a dialer that acts
# on behalf of a named endpoint (a replica's fabric RPC, a direct KV
# push) tags its socket with that endpoint's ADVERTISED addr, so the
# chaos hooks can match "frames between peers A and B" without the
# ephemeral local port lying about who is talking.  ``socket.socket``
# is slotted (no arbitrary attributes), hence the side table; weak keys
# let closed sockets vanish without bookkeeping.
_sock_idents: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def tag_socket(sock, ident: str) -> None:
    """Record that ``sock`` speaks for the endpoint ``ident``
    (``host:port``).  Best-effort: untaggable objects (test doubles
    without weakref support) are ignored."""
    try:
        _sock_idents[sock] = str(ident)
    except TypeError:
        pass


def sock_ident(sock) -> Optional[str]:
    """The advertised endpoint ``sock`` was tagged with, or None."""
    try:
        return _sock_idents.get(sock)
    except TypeError:
        return None


def new_token() -> str:
    """Fresh per-cluster auth token (scheduler generates one per bring-up)."""
    return os.urandom(16).hex()


def load_token(environ=os.environ) -> str:
    """Resolve the cluster token a task was launched with.

    Prefers a mode-0600 token *file* (``TPUMESOS_TOKEN_FILE``) over the plain
    env var: env vars leak through Mesos state endpoints and /proc environ,
    so co-located backends deliver the secret out-of-band (advisor finding on
    spec.py token delivery).
    """
    path = environ.get(TOKEN_FILE_ENV)
    if path:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    return environ.get(TOKEN_ENV, "")


def _tag(token: str, body: bytes) -> bytes:
    return hmac.new(token.encode("utf-8"), body, hashlib.sha256).digest()


def encode(obj: Any, token: str = "") -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    tag = _tag(token, body)
    return _LEN.pack(TAG_SIZE + len(body)) + tag + body


def _decode_body(payload: bytes, token: str) -> Any:
    if len(payload) < TAG_SIZE:
        raise WireError("frame shorter than auth tag")
    tag, body = payload[:TAG_SIZE], payload[TAG_SIZE:]
    if not hmac.compare_digest(tag, _tag(token, body)):
        raise WireError("bad auth tag")
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad JSON body: {e}") from e


def send_msg(sock: socket.socket, obj: Any, token: str = "") -> None:
    data = encode(obj, token)
    hook = _chaos_send    # snapshot: a concurrent uninstall must not
    if hook is not None and hook(sock, data):   # turn this into a None call
        return      # frame consumed (chaos drop)
    sock.sendall(data)


def _decode_raw(payload: bytes, token: str) -> RawFrame:
    if len(payload) < TAG_SIZE + _LEN.size:
        raise WireError("raw frame shorter than tag + meta length")
    tag, rest = payload[:TAG_SIZE], memoryview(payload)[TAG_SIZE:]
    if not hmac.compare_digest(tag, _tag(token, rest)):
        raise WireError("bad auth tag on raw frame")
    (meta_len,) = _LEN.unpack(rest[:_LEN.size])
    if meta_len > MAX_RAW_META or _LEN.size + meta_len > len(rest):
        raise WireError(f"bad raw meta length {meta_len}")
    # JSON, never pickle: an authenticated peer must not gain code
    # execution from a crafted meta header (clients hold the token too).
    try:
        meta = json.loads(
            bytes(rest[_LEN.size:_LEN.size + meta_len]).decode("utf-8"))
    except Exception as e:
        raise WireError(f"bad raw meta: {e!r}") from e
    return RawFrame(meta, bytes(rest[_LEN.size + meta_len:]))


def encode_raw(meta: Any, body, token: str = "") -> bytes:
    """One raw frame as contiguous bytes (tests / chaos hooks; the hot
    path is :func:`send_raw_msg`, which never concatenates the body)."""
    header, mv = _raw_parts(meta, body, token)
    return header + bytes(mv)


def _raw_parts(meta: Any, body, token: str):
    """(header bytes, body memoryview) for one raw frame."""
    meta_b = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    if len(meta_b) > MAX_RAW_META:
        raise WireError(f"raw meta of {len(meta_b)} bytes exceeds limit")
    mv = memoryview(body).cast("B") if not isinstance(body, bytes) \
        else memoryview(body)
    length = TAG_SIZE + _LEN.size + len(meta_b) + len(mv)
    if length > MAX_RAW_FRAME:
        raise WireError(f"raw frame of {length} bytes exceeds limit")
    ml = _LEN.pack(len(meta_b))
    mac = hmac.new(token.encode("utf-8"), ml, hashlib.sha256)
    mac.update(meta_b)
    mac.update(mv)
    header = _LEN.pack(RAW_FLAG | length) + mac.digest() + ml + meta_b
    return header, mv


def send_raw_msg(sock: socket.socket, meta: Any, body,
                 token: str = "") -> None:
    """Send one raw frame: ``meta`` (JSON-encodable header) + ``body`` (bytes
    or any buffer), HMAC-tagged like every other frame.  The body goes
    to the socket straight from the caller's buffer — no text encoding
    or concatenation of multi-MB payloads."""
    header, mv = _raw_parts(meta, body, token)
    hook = _chaos_send    # snapshot against a concurrent uninstall
    if hook is not None:
        data = header + bytes(mv)   # chaos-only path; copies are fine
        if hook(sock, data):
            return
        sock.sendall(data)
        return
    sock.sendall(header)
    sock.sendall(mv)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise WireError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, token: str = "",
             allow_raw: bool = False) -> Any:
    """Next message: a decoded JSON object, or (with ``allow_raw``) a
    :class:`RawFrame`.  Raw framing is OPT-IN per stream: an
    unauthenticated peer could otherwise claim a ``MAX_RAW_FRAME``
    (1 GiB) length and force that much buffering before the tag check,
    so listeners that never expect KV payloads keep the 64 MiB
    pre-auth bound and reject the raw bit outright."""
    hook = _chaos_recv    # snapshot against a concurrent uninstall
    if hook is not None:
        hook(sock)
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length & RAW_FLAG:
        if not allow_raw:
            raise WireError("raw frame not accepted on this stream")
        length &= ~RAW_FLAG
        if length > MAX_RAW_FRAME:
            raise WireError(f"raw frame of {length} bytes exceeds limit")
        return _decode_raw(_recv_exact(sock, length), token)
    if length > MAX_FRAME:
        raise WireError(f"frame of {length} bytes exceeds limit")
    return _decode_body(_recv_exact(sock, length), token)


class Framer:
    """Incremental decoder for non-blocking / selector-driven loops.

    The scheduler's rendezvous loop (the analogue of the reference's 0.1s
    select poll, scheduler.py:341-361, but event-driven) feeds raw bytes in
    and pulls complete decoded messages out.
    """

    def __init__(self, token: str = "", allow_raw: bool = False) -> None:
        self._token = token
        self._buf = bytearray()
        # Raw framing is opt-in per stream (see recv_msg): only links
        # that legitimately carry KV payloads (replica servers, mux
        # connections) widen their pre-auth buffering bound to
        # MAX_RAW_FRAME; everything else rejects the raw bit at the
        # 4-byte prefix, before any body buffers.
        self._allow_raw = allow_raw

    def feed(self, data: bytes) -> List[Any]:
        self._buf.extend(data)
        out = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (length,) = _LEN.unpack(bytes(self._buf[: _LEN.size]))
            raw = bool(length & RAW_FLAG)
            if raw:
                if not self._allow_raw:
                    raise WireError("raw frame not accepted on this "
                                    "stream")
                length &= ~RAW_FLAG
                if length > MAX_RAW_FRAME:
                    raise WireError(f"raw frame of {length} bytes "
                                    f"exceeds limit")
            elif length > MAX_FRAME:
                raise WireError(f"frame of {length} bytes exceeds limit")
            end = _LEN.size + length
            if len(self._buf) < end:
                break
            payload = bytes(self._buf[_LEN.size : end])
            del self._buf[:end]
            out.append(_decode_raw(payload, self._token) if raw
                       else _decode_body(payload, self._token))
        return out


def iter_msgs(sock: socket.socket, framer: "Framer"):
    """Decoded messages from a blocking socket, until EOF ends the
    generator — the shared read loop of every long-lived control
    connection (fleet gateway/replica/registry/mux client).  A bad
    frame raises :class:`WireError`; socket errors propagate.

    Consults the chaos recv hook per blocking read, like
    :func:`recv_msg` — so fault plans can sever/delay the fleet's
    long-lived links (mux connections, heartbeat streams, the
    drain-migration KV handoff) mid-stream, not just the scheduler's
    one-shot recv paths."""
    while True:
        hook = _chaos_recv      # snapshot against a concurrent uninstall
        if hook is not None:
            hook(sock)
        data = sock.recv(65536)
        if not data:
            return
        for msg in framer.feed(data):
            yield msg


def connect(addr: str, timeout: Optional[float] = 30.0) -> socket.socket:
    """Dial a ``host:port`` string (the form used throughout the control plane)."""
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(timeout)
    _nodelay(sock)
    return sock


def _nodelay(sock: socket.socket) -> None:
    """Disable Nagle.  Every wire exchange is a small framed request
    waiting on a small framed reply — exactly the pattern where Nagle
    batching + the peer's delayed ACK serializes into ~40ms stalls
    per round trip.  Best-effort: a transport without the option
    (e.g. AF_UNIX) just skips it."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass


def reuseport_available() -> bool:
    """Whether this platform supports ``SO_REUSEPORT`` (N processes
    sharing one listening port, the kernel load-balancing accepts) —
    the multi-process gateway's preferred deployment shape."""
    return hasattr(socket, "SO_REUSEPORT")


def bind_ephemeral(host: str = "0.0.0.0", port: int = 0,
                   reuseport: bool = False) -> socket.socket:
    """Bind a listening socket on an OS-assigned port (reference pattern at
    scheduler.py:325-328 / server.py:18-21).  ``port`` pins a specific
    port instead (the fleet gateway's stable front-door address).
    ``reuseport`` additionally sets ``SO_REUSEPORT`` so N gateway
    PROCESSES can share the pinned port (raises ``OSError`` where the
    platform lacks it — callers fall back to per-process ports behind
    the ``gateways`` discovery op)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if reuseport:
        if not reuseport_available():
            sock.close()
            raise OSError("SO_REUSEPORT is not available on this platform")
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def wake_listener(sock: Optional[socket.socket]) -> None:
    """Wake a thread blocked in ``accept()`` on this listening socket.

    ``close()`` alone does NOT interrupt a blocked ``accept`` on Linux —
    the syscall stays parked until the next real connection, so every
    ``stop()`` that merely closed its listener used to burn the full
    thread-join timeout (2-5s per component; whole seconds of pure
    teardown per fleet test).  A throwaway self-connection makes the
    accept return; the loop re-checks its stop flag and exits.  Call
    AFTER setting the stop flag and BEFORE closing the socket."""
    if sock is None:
        return
    try:
        host, port = sock.getsockname()[:2]
        if host == "0.0.0.0":
            host = "127.0.0.1"
        elif host == "::":
            host = "::1"
        poke = socket.create_connection((host, port), timeout=0.5)
        poke.close()
    except OSError:
        pass


def shutdown_socket(sock: Optional[socket.socket]) -> None:
    """Abortively end a connection another thread may be blocked
    reading: ``close()`` alone does NOT send the FIN (or wake the
    reader) while a recv syscall still holds the socket's kernel
    reference — the connection just sits half-alive until that recv
    returns, so peers of an in-process ``stop()`` never saw EOF and
    rode their full timeouts (the recv-side sibling of the
    ``wake_listener`` accept pathology).  ``shutdown(SHUT_RDWR)``
    tears the stream down NOW: the local reader unblocks and the peer
    gets its EOF immediately.  Call before ``close()``."""
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass


def sock_addr(sock: socket.socket, advertise_host: Optional[str] = None) -> str:
    host, port = sock.getsockname()[:2]
    if advertise_host:
        host = advertise_host
    elif host in ("0.0.0.0", "::"):
        host = socket.gethostbyname(socket.gethostname())
    return f"{host}:{port}"


# -- the event-loop serve core ----------------------------------------------
#
# The thread-per-connection accept loops (one OS thread blocked in recv
# per peer) cap the front door at tens-to-hundreds of concurrent client
# links — the same shape as the reference's one-scheduler-process
# rendezvous server.  WireServer multiplexes EVERY connection of one
# listener onto a single selector-driven thread: the Framer already
# parses incrementally (byte-at-a-time if it must), so reads are
# non-blocking feeds, writes are buffered and flushed as the socket
# drains, and the HMAC / raw-bit / pre-auth-bound discipline is exactly
# the Framer's.  The threaded connect/send_msg/recv_msg CLIENT api
# stays for low-fanout links (scheduler rendezvous, heartbeats, mux
# links to replicas); only listeners that must scale (gateway,
# registry) ride this.


class WireConn:
    """One accepted connection on a :class:`WireServer`.

    ``send``/``send_raw`` may be called from ANY thread: frames append
    to a per-connection write buffer and the event loop flushes them as
    the socket drains — a slow reader therefore never blocks the caller
    (a gateway worker) or the loop; past ``max_buffer`` of backlog the
    connection is DROPPED instead (backpressure must bound memory, and
    a peer that cannot keep up with its own replies is as good as
    gone).  Handlers may stash per-connection state as plain attributes
    (the registry keys heartbeat EOFs that way).

    A connection accepted on an INGRESS listener (``add_ingress``)
    carries a ``protocol`` object instead of the HMAC framer: raw
    socket bytes go to ``protocol.data_received(data)`` (an exception
    drops the connection — the protocol's rejection surface), replies
    go out through ``send_bytes``, and on drop/close the protocol's own
    ``on_close()`` fires INSTEAD of the server's ``on_close`` hook (an
    ingress peer must never be mistaken for a wire peer — the registry
    keys replica EOFs off that hook).  ``deadline`` (a monotonic
    timestamp, maintained via ``server._watch``) is the slow-loris
    bound: a connection that blows past it is swept closed by the
    loop."""

    def __init__(self, server: "WireServer", sock: socket.socket,
                 peer: str):
        self._server = server
        self._sock = sock
        self.peer = peer
        self._framer = Framer(server.token, allow_raw=server.allow_raw)
        self._out = bytearray()
        self._wlock = threading.Lock()
        self._closed = False
        self._close_after_flush = False
        self._events = selectors.EVENT_READ
        self.drop_reason: Optional[str] = None
        self.protocol: Optional[Any] = None
        self.deadline: Optional[float] = None

    @property
    def closed(self) -> bool:
        return self._closed

    def send(self, obj: Any) -> bool:
        """Queue one JSON frame; False when the connection is (being)
        dropped.  Best-effort by design: a vanished client is not an
        error the serving path should care about."""
        return self._enqueue(encode(obj, self._server.token))

    def send_raw(self, meta: Any, body) -> bool:
        """Queue one raw binary frame (meta + body, HMAC-tagged)."""
        header, mv = _raw_parts(meta, body, self._server.token)
        return self._enqueue(header + bytes(mv))

    def send_bytes(self, data: bytes) -> bool:
        """Queue pre-encoded bytes verbatim (no framing, no HMAC) —
        the ingress-protocol reply path (HTTP responses, SSE frames).
        Same buffering/overflow discipline as ``send``."""
        return self._enqueue(bytes(data))

    def _enqueue(self, frame: bytes) -> bool:
        hook = _chaos_send     # snapshot against a concurrent uninstall
        if hook is not None:
            try:
                if hook(self._sock, frame):
                    return True         # chaos drop: frame swallowed
            except OSError:
                self._server._request_close(self)   # chaos sever
                return False
        with self._wlock:
            if self._closed:
                return False
            self._out += frame
            over = len(self._out) > self._server.max_buffer
        if over:
            self.drop_reason = "write-buffer overflow (slow reader)"
            self._server._request_close(self)
            return False
        self._server._mark_writable(self)
        return True

    def close(self) -> None:
        """Flush whatever is already queued, then close (thread-safe)."""
        self._close_after_flush = True
        self._server._mark_writable(self)


class WireServer:
    """A selector-driven accept/read/dispatch/write loop over one
    listening socket — the serve-side core the fleet gateway and the
    replica registry ride (docs/SERVING.md "Front-door scaling").

    ``handler(conn, msg)`` runs ON THE LOOP THREAD for every decoded
    message (a dict, or a :class:`RawFrame` when ``allow_raw``) — it
    must not block; hand real work to a pool and reply later via
    ``conn.send`` (thread-safe, buffered).  A handler exception, a bad
    frame (HMAC failure, oversized length, the raw bit on a
    non-``allow_raw`` stream — all rejected by the Framer at the same
    pre-auth bounds as the threaded path), or write-buffer overflow
    drops THAT connection and nothing else.  ``on_close(conn)`` fires
    once per dropped/closed connection (not at server stop).

    The chaos hooks (:func:`set_chaos`) are consulted exactly like the
    threaded path's: the send hook per queued frame, the recv hook per
    read batch — so fault plans reach event-loop links too (a chaos
    delay sleeps the loop thread; chaos is a test-only surface).

    ``stop()`` wakes the loop through a self-pipe; :func:`wake_listener`
    on the listening socket also unblocks it (the poke lands as an
    accept event), so the threaded stop discipline keeps working."""

    def __init__(self, handler: Callable[[WireConn, Any], None],
                 token: str = "", host: str = "127.0.0.1", port: int = 0,
                 allow_raw: bool = False, name: str = "wire-server",
                 max_buffer: int = 64 * 1024 * 1024,
                 on_close: Optional[Callable[[WireConn], None]] = None,
                 advertise_host: Optional[str] = None,
                 reuseport: bool = False):
        self.handler = handler
        self.token = token
        self.host = host
        self.port = int(port)
        self.allow_raw = bool(allow_raw)
        self.name = name
        self.max_buffer = int(max_buffer)
        self.on_close = on_close
        self.advertise_host = advertise_host
        self.reuseport = bool(reuseport)
        self.addr: Optional[str] = None
        self._listen: Optional[socket.socket] = None
        self._sel: Optional[selectors.BaseSelector] = None
        self._waker_r: Optional[socket.socket] = None
        self._waker_w: Optional[socket.socket] = None
        self._conns: set = set()
        self._pending: set = set()          # conns with queued writes
        self._pending_close: set = set()
        self._plock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Ingress listeners: (factory, host, port) requested pre-start;
        # bound sockets + addrs filled in by start().
        self._ingress: List[tuple] = []
        self._ingress_socks: List[socket.socket] = []
        self.ingress_addrs: List[str] = []
        # Connections with a live slow-loris deadline (loop-thread only).
        self._timed: set = set()
        from tfmesos_tpu.utils.logging import get_logger
        self.log = get_logger("tfmesos_tpu.wire")

    # -- lifecycle ---------------------------------------------------------

    def add_ingress(self, factory: Callable[[WireConn], Any],
                    host: str = "127.0.0.1", port: int = 0) -> None:
        """Register an EXTRA listener on the same event loop whose
        accepted connections speak a caller-defined protocol instead of
        the HMAC wire framing (the HTTP/SSE edge).  ``factory(conn)``
        runs per accept and returns the protocol object: raw bytes go
        to ``protocol.data_received(data)`` (raise to drop the
        connection), replies ride ``conn.send_bytes``, and
        ``protocol.on_close()`` (optional) fires when the connection
        dies.  Must be called BEFORE ``start()``."""
        if self._thread is not None:
            raise RuntimeError("add_ingress must precede start()")
        self._ingress.append((factory, host, int(port)))

    def start(self) -> "WireServer":
        self._listen = bind_ephemeral(self.host, port=self.port,
                                      reuseport=self.reuseport)
        self._listen.setblocking(False)
        adv = self.advertise_host or (
            None if self.host in ("0.0.0.0", "::") else self.host)
        self.addr = sock_addr(self._listen, advertise_host=adv)
        self._sel = selectors.DefaultSelector()
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._sel.register(self._listen, selectors.EVENT_READ, "listen")
        self._sel.register(self._waker_r, selectors.EVENT_READ, "waker")
        for factory, host, port in self._ingress:
            sock = bind_ephemeral(host, port=port)
            sock.setblocking(False)
            self._ingress_socks.append(sock)
            self.ingress_addrs.append(sock_addr(
                sock, advertise_host=adv if host in ("0.0.0.0", "::")
                else host))
            self._sel.register(sock, selectors.EVENT_READ,
                               ("ingress", sock, factory))
        self._thread = threading.Thread(target=self._loop, name=self.name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the loop and close every connection.  Abrupt by design
        (peers see the close, in-flight replies may be cut) — which is
        also what makes it double as the bench's gateway 'SIGKILL'."""
        self._stop.set()
        self._wake()
        # Belt and braces: the waker is the fast path, the accept poke
        # is the one that must KEEP working (the fleet-wide stop
        # discipline since the wake_listener fix).
        wake_listener(self._listen)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def connections(self) -> List[WireConn]:
        with self._plock:
            return list(self._conns)

    # -- cross-thread signaling --------------------------------------------

    def _wake(self) -> None:
        w = self._waker_w
        if w is None:
            return
        try:
            w.send(b"\0")
        except OSError:
            pass

    def _mark_writable(self, conn: WireConn) -> None:
        with self._plock:
            self._pending.add(conn)
        self._wake()

    def _request_close(self, conn: WireConn) -> None:
        with self._plock:
            self._pending_close.add(conn)
        self._wake()

    def _watch(self, conn: WireConn) -> None:
        """Track ``conn`` in the deadline sweep.  Thread-safe: ingress
        protocols parse on the loop thread, but a keep-alive response
        finishing on a gateway worker thread re-arms the idle deadline
        from there.  The wake matters: with no timed conns the loop
        selects on a 5s backstop, far past the idle keep-alive
        deadline it must now enforce."""
        with self._plock:
            self._timed.add(conn)
        self._wake()

    # -- the loop ----------------------------------------------------------

    def _sweep_timed(self) -> None:
        if not self._timed:
            return
        now = time.monotonic()
        with self._plock:
            timed = list(self._timed)
        for conn in timed:
            if conn._closed or conn.deadline is None:
                self._timed.discard(conn)
            elif now > conn.deadline:
                self._timed.discard(conn)
                self._close_conn(conn, "ingress deadline (slow peer)")

    def _loop(self) -> None:
        sel = self._sel
        try:
            while not self._stop.is_set():
                # The waker (and wake_listener's accept poke) are what
                # actually end the wait; the timeout is only the
                # backstop if both ever fail — except while ingress
                # connections carry slow-loris deadlines, when the wait
                # shortens so the sweep stays timely.
                timeout = 0.25 if self._timed else 5.0
                for key, mask in sel.select(timeout=timeout):
                    tag = key.data
                    if tag == "listen":
                        self._accept_ready()
                    elif tag == "waker":
                        try:
                            while self._waker_r.recv(4096):
                                pass
                        except OSError:
                            pass
                    elif isinstance(tag, tuple) and tag[0] == "ingress":
                        self._accept_ready(listen=tag[1], factory=tag[2])
                    else:
                        if mask & selectors.EVENT_READ:
                            self._read_ready(tag)
                        if mask & selectors.EVENT_WRITE \
                                and not tag._closed:
                            self._flush(tag)
                self._service_pending()
                self._sweep_timed()
        finally:
            with self._plock:
                conns = list(self._conns)
                self._conns.clear()
                self._pending.clear()
                self._pending_close.clear()
            for conn in conns:
                with conn._wlock:
                    conn._closed = True
                try:
                    conn._sock.close()
                except OSError:
                    pass
            for sock in ([self._listen, self._waker_r, self._waker_w]
                         + self._ingress_socks):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            try:
                sel.close()
            except OSError:
                pass

    def _service_pending(self) -> None:
        with self._plock:
            closes = list(self._pending_close)
            self._pending_close.clear()
            pend = list(self._pending)
            self._pending.clear()
        for conn in closes:
            self._close_conn(conn, conn.drop_reason or "closed")
        for conn in pend:
            if not conn._closed:
                self._flush(conn)

    def _accept_ready(self, listen: Optional[socket.socket] = None,
                      factory: Optional[Callable] = None) -> None:
        listen = listen if listen is not None else self._listen
        while True:
            try:
                sock, peer = listen.accept()
            except BlockingIOError:
                return
            except OSError:
                return              # listener closed (stopping)
            sock.setblocking(False)
            _nodelay(sock)
            conn = WireConn(self, sock, f"{peer[0]}:{peer[1]}"
                            if isinstance(peer, tuple) else str(peer))
            if factory is not None:
                try:
                    conn.protocol = factory(conn)
                except Exception:
                    self.log.exception("%s: ingress factory failed",
                                       self.name)
                    try:
                        sock.close()
                    except OSError:
                        pass
                    continue
            with self._plock:
                self._conns.add(conn)
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (ValueError, OSError):
                self._close_conn(conn, "selector register failed")

    def _read_ready(self, conn: WireConn) -> None:
        hook = _chaos_recv     # snapshot against a concurrent uninstall
        if hook is not None:
            try:
                hook(conn._sock)
            except OSError as e:
                self._close_conn(conn, f"chaos: {e}")
                return
        try:
            data = conn._sock.recv(262144)
        except BlockingIOError:
            return
        except OSError as e:
            self._close_conn(conn, str(e))
            return
        if not data:
            self._close_conn(conn, "eof")
            return
        proto = conn.protocol
        if proto is not None:
            # Ingress connection: the protocol object parses its own
            # framing under its own byte bounds; raising is its
            # rejection surface (malformed request, oversized body).
            try:
                proto.data_received(data)
            except Exception as e:
                self.log.warning("%s: dropping ingress connection from "
                                 "%s: %s", self.name, conn.peer, e)
                self._close_conn(conn, f"ingress error: {e}")
            return
        try:
            msgs = conn._framer.feed(data)
        except WireError as e:
            # Same rejection surface as the threaded loops: HMAC
            # failure, oversize at the 4-byte prefix, the raw bit on a
            # stream that never opted in — the connection drops, the
            # pre-auth buffering bound held.
            self.log.warning("%s: dropping connection from %s: %s",
                             self.name, conn.peer, e)
            self._close_conn(conn, f"wire error: {e}")
            return
        for msg in msgs:
            if conn._closed:
                return
            try:
                self.handler(conn, msg)
            except Exception:
                self.log.exception("%s: handler failed; dropping "
                                   "connection from %s", self.name,
                                   conn.peer)
                self._close_conn(conn, "handler error")
                return

    def _flush(self, conn: WireConn) -> None:
        err: Optional[OSError] = None
        with conn._wlock:
            buf = conn._out
            if buf:
                try:
                    n = conn._sock.send(buf)
                    del buf[:n]
                except BlockingIOError:
                    pass
                except OSError as e:
                    err = e
            has_more = bool(buf)
        if err is not None:
            self._close_conn(conn, str(err))
            return
        want = selectors.EVENT_READ | (selectors.EVENT_WRITE
                                       if has_more else 0)
        if want != conn._events:
            try:
                self._sel.modify(conn._sock, want, conn)
                conn._events = want
            except (KeyError, ValueError, OSError):
                pass
        if not has_more and conn._close_after_flush:
            self._close_conn(conn, "closed")

    def _close_conn(self, conn: WireConn, why: str) -> None:
        with conn._wlock:
            if conn._closed:
                return
            conn._closed = True
            conn._out = bytearray()
        try:
            self._sel.unregister(conn._sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn._sock.close()
        except OSError:
            pass
        with self._plock:
            self._conns.discard(conn)
            self._pending.discard(conn)
            self._pending_close.discard(conn)
        self._timed.discard(conn)
        if conn.protocol is not None:
            # Ingress connections notify their OWN protocol, never the
            # server-level hook: that hook carries wire-peer semantics
            # (the registry attributes replica EOFs through it).
            cb = getattr(conn.protocol, "on_close", None)
            if cb is not None:
                try:
                    cb()
                except Exception:
                    self.log.exception("%s: ingress on_close failed",
                                       self.name)
        elif self.on_close is not None:
            try:
                self.on_close(conn)
            except Exception:
                self.log.exception("%s: on_close callback failed",
                                   self.name)
