"""Authenticated control-plane wire protocol.

The reference framework (tfmesos/utils.py:6-15) frames messages as a 4-byte
big-endian length followed by a *pickle* payload, unauthenticated.  That design
is reproduced here in shape only: we keep the simple length-prefixed framing
(so the control plane stays a handful of syscalls per message) but replace the
encoding with JSON and add an HMAC-SHA256 tag keyed by a per-cluster token, so
a task can only join the rendezvous if it was launched by our scheduler.

Frame layout::

    +----------------+----------------------+------------------+
    | 4B len (BE)    | 32B HMAC-SHA256 tag  | JSON body (UTF8) |
    +----------------+----------------------+------------------+

``len`` counts tag + body.  When ``token`` is empty the tag is still present
but computed with the empty key, keeping the frame layout static.
"""

from __future__ import annotations

import hmac
import hashlib
import json
import os
import socket
import struct
from typing import Any, List, Optional

_LEN = struct.Struct(">I")
TAG_SIZE = hashlib.sha256().digest_size  # 32
MAX_FRAME = 64 * 1024 * 1024  # sanity bound; control messages are tiny

TOKEN_ENV = "TPUMESOS_TOKEN"
TOKEN_FILE_ENV = "TPUMESOS_TOKEN_FILE"


class WireError(Exception):
    """Malformed, oversized, or unauthenticated frame."""


# Fault-injection hooks (chaos.FaultPlan.install): consulted per framed
# send/recv when set, so tests can sever/delay/truncate/drop traffic on a
# live connection deterministically.  ``None`` (the default) costs one
# attribute load per message.
_chaos_send = None      # Optional[Callable[[socket, bytes], bool]]
_chaos_recv = None      # Optional[Callable[[socket], None]]


def set_chaos(send=None, recv=None) -> None:
    """Install (or clear, with Nones) the process-global wire fault hooks.

    ``send(sock, frame) -> bool`` runs before every ``send_msg`` frame
    hits the socket — it may sleep (delay), raise OSError after closing
    the socket (sever), write a partial frame then raise (truncate), or
    return True to silently swallow the frame (drop).  ``recv(sock)``
    runs before every blocking ``recv_msg`` and may sleep or sever.
    """
    global _chaos_send, _chaos_recv
    _chaos_send = send
    _chaos_recv = recv


def new_token() -> str:
    """Fresh per-cluster auth token (scheduler generates one per bring-up)."""
    return os.urandom(16).hex()


def load_token(environ=os.environ) -> str:
    """Resolve the cluster token a task was launched with.

    Prefers a mode-0600 token *file* (``TPUMESOS_TOKEN_FILE``) over the plain
    env var: env vars leak through Mesos state endpoints and /proc environ,
    so co-located backends deliver the secret out-of-band (advisor finding on
    spec.py token delivery).
    """
    path = environ.get(TOKEN_FILE_ENV)
    if path:
        with open(path, "r", encoding="utf-8") as f:
            return f.read().strip()
    return environ.get(TOKEN_ENV, "")


def _tag(token: str, body: bytes) -> bytes:
    return hmac.new(token.encode("utf-8"), body, hashlib.sha256).digest()


def encode(obj: Any, token: str = "") -> bytes:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    tag = _tag(token, body)
    return _LEN.pack(TAG_SIZE + len(body)) + tag + body


def _decode_body(payload: bytes, token: str) -> Any:
    if len(payload) < TAG_SIZE:
        raise WireError("frame shorter than auth tag")
    tag, body = payload[:TAG_SIZE], payload[TAG_SIZE:]
    if not hmac.compare_digest(tag, _tag(token, body)):
        raise WireError("bad auth tag")
    try:
        return json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireError(f"bad JSON body: {e}") from e


def send_msg(sock: socket.socket, obj: Any, token: str = "") -> None:
    data = encode(obj, token)
    hook = _chaos_send    # snapshot: a concurrent uninstall must not
    if hook is not None and hook(sock, data):   # turn this into a None call
        return      # frame consumed (chaos drop)
    sock.sendall(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise WireError("connection closed mid-frame")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_msg(sock: socket.socket, token: str = "") -> Any:
    hook = _chaos_recv    # snapshot against a concurrent uninstall
    if hook is not None:
        hook(sock)
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise WireError(f"frame of {length} bytes exceeds limit")
    return _decode_body(_recv_exact(sock, length), token)


class Framer:
    """Incremental decoder for non-blocking / selector-driven loops.

    The scheduler's rendezvous loop (the analogue of the reference's 0.1s
    select poll, scheduler.py:341-361, but event-driven) feeds raw bytes in
    and pulls complete decoded messages out.
    """

    def __init__(self, token: str = "") -> None:
        self._token = token
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Any]:
        self._buf.extend(data)
        out = []
        while True:
            if len(self._buf) < _LEN.size:
                break
            (length,) = _LEN.unpack(bytes(self._buf[: _LEN.size]))
            if length > MAX_FRAME:
                raise WireError(f"frame of {length} bytes exceeds limit")
            end = _LEN.size + length
            if len(self._buf) < end:
                break
            payload = bytes(self._buf[_LEN.size : end])
            del self._buf[:end]
            out.append(_decode_body(payload, self._token))
        return out


def iter_msgs(sock: socket.socket, framer: "Framer"):
    """Decoded messages from a blocking socket, until EOF ends the
    generator — the shared read loop of every long-lived control
    connection (fleet gateway/replica/registry/mux client).  A bad
    frame raises :class:`WireError`; socket errors propagate."""
    while True:
        data = sock.recv(65536)
        if not data:
            return
        for msg in framer.feed(data):
            yield msg


def connect(addr: str, timeout: Optional[float] = 30.0) -> socket.socket:
    """Dial a ``host:port`` string (the form used throughout the control plane)."""
    host, port = addr.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(timeout)
    return sock


def bind_ephemeral(host: str = "0.0.0.0", port: int = 0) -> socket.socket:
    """Bind a listening socket on an OS-assigned port (reference pattern at
    scheduler.py:325-328 / server.py:18-21).  ``port`` pins a specific
    port instead (the fleet gateway's stable front-door address)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((host, port))
    sock.listen(128)
    return sock


def sock_addr(sock: socket.socket, advertise_host: Optional[str] = None) -> str:
    host, port = sock.getsockname()[:2]
    if advertise_host:
        host = advertise_host
    elif host in ("0.0.0.0", "::"):
        host = socket.gethostbyname(socket.gethostname())
    return f"{host}:{port}"
