"""Sharded training loop machinery.

The reference's training mechanics live in user scripts: per-worker sessions
pushing gradients to parameter servers, `SyncReplicasOptimizer` for sync SGD,
`Supervisor` for init/recovery (mnist_replica.py:116-210).  All of that
collapses here into one jit'd step over a GSPMD mesh: params carry
NamedShardings (FSDP/TP/etc.), the batch is sharded over the data axes, and
XLA inserts the gradient all-reduce that parameter servers used to be.
Sync-SGD is therefore the *default* semantics; async PS has no TPU analogue
(and converges worse anyway).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfmesos_tpu.parallel.sharding import (batch_sharding, data_axes,
                                           fsdp_sharding_tree, place_tree)
from tfmesos_tpu.utils.logging import get_logger
from tfmesos_tpu.utils.profiling import trace

log = get_logger("tfmesos_tpu.trainer")


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


def make_train_step(loss_fn: Callable, optimizer: optax.GradientTransformation,
                    mesh: Optional[Mesh] = None,
                    param_specs: Optional[Any] = None,
                    batch_spec_tree: Optional[Any] = None,
                    postprocess: Optional[Callable] = None,
                    steps_per_call: int = 1,
                    grad_accum: int = 1,
                    scan_unroll: int = 1,
                    grads_fn: Optional[Callable] = None):
    """Build the jit'd train step.

    ``loss_fn(params, batch) -> (loss, metrics)``.  With a mesh, params/opt
    state are placed per ``param_specs`` (default: FSDP rules) and the batch
    per ``batch_spec_tree`` (default: leading dim over data axes); buffers
    are donated so params update in place.  ``postprocess`` (e.g. the NMF
    non-negativity projection) runs on the updated params inside the step.

    ``steps_per_call > 1`` compiles a ``lax.scan`` of that many optimizer
    steps into ONE dispatch: batch leaves carry a leading ``[steps_per_call,
    ...]`` dim and the host pays one round-trip per K steps — the dominant
    cost for small models on remote-attached or latency-bound runtimes.
    Returned metrics are the last step's.

    ``scan_unroll`` unrolls the fused-step ``lax.scan`` body that many
    iterations (must divide ``steps_per_call``): for tiny models the
    per-iteration scan overhead dominates the math, and unrolling lets XLA
    fuse across consecutive optimizer steps — same arithmetic, fewer
    kernel launches.  Leave at 1 for models whose step is compute-bound.

    ``grad_accum > 1`` splits each step's batch into that many microbatches
    and averages their gradients before the single optimizer update — the
    full-batch step for losses that are per-example means (equal micro
    sizes), at 1/grad_accum the activation memory, since each microbatch's
    backward completes before the next begins.  Loss terms that are
    *batch statistics* (e.g. MoE load-balance fractions) are computed per
    microbatch, a slightly different objective.  Returned metrics are
    microbatch means.  The per-step batch dim must divide evenly (and stay
    divisible by the data-axis size).

    ``grads_fn(params, batch) -> (grads, loss, metrics)`` replaces the
    default ``jax.value_and_grad(loss_fn)`` pass for schedules autodiff
    cannot express — e.g. ``transformer.train_step_1f1b``'s fused-1F1B
    pipeline pass.  Exclusive with ``grad_accum`` (such passes microbatch
    internally); ``loss_fn`` is ignored when given.
    """

    if grads_fn is not None and grad_accum != 1:
        raise ValueError("grads_fn and grad_accum are exclusive: a custom "
                         "gradient pass (e.g. the 1F1B pipeline step) does "
                         "its own microbatching")

    def grads_and_metrics(params, batch):
        if grads_fn is not None:
            # Custom gradient pass — e.g. transformer.train_step_1f1b,
            # whose fused fwd+bwd schedule jax.value_and_grad cannot
            # express.  Contract: (grads, loss, metrics).
            return grads_fn(params, batch)
        if grad_accum == 1:
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            return grads, loss, metrics
        micro = jax.tree_util.tree_map(
            lambda x: x.reshape(grad_accum, x.shape[0] // grad_accum,
                                *x.shape[1:]), batch)

        def acc(carry, mb):
            gsum, lsum = carry
            (loss, metrics), g = jax.value_and_grad(
                loss_fn, has_aux=True)(params, mb)
            gsum = jax.tree_util.tree_map(jnp.add, gsum, g)
            return (gsum, lsum + loss), metrics

        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (gsum, lsum), metrics = jax.lax.scan(
            acc, (zeros, jnp.zeros((), jnp.float32)), micro)
        grads = jax.tree_util.tree_map(
            lambda g, p: (g / grad_accum).astype(p.dtype), gsum, params)
        # Microbatch MEANS for every metric, matching the reported loss
        # (exp(mean loss) still differs from mean perplexity — means of
        # nonlinear metrics are approximations either way).
        mean_metrics = jax.tree_util.tree_map(lambda m: jnp.mean(m, axis=0),
                                              metrics)
        return grads, lsum / grad_accum, mean_metrics

    def one_step(params, opt_state, batch):
        grads, loss, metrics = grads_and_metrics(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if postprocess is not None:
            params = postprocess(params)
        metrics = dict(metrics)
        metrics["loss"] = loss
        return params, opt_state, metrics

    if scan_unroll < 1 or steps_per_call % scan_unroll:
        raise ValueError(f"scan_unroll ({scan_unroll}) must divide "
                         f"steps_per_call ({steps_per_call})")
    if steps_per_call == 1:
        step_fn = one_step
    else:
        def step_fn(params, opt_state, batch):
            def body(carry, micro):
                p, o = carry
                p, o, metrics = one_step(p, o, micro)
                return (p, o), metrics
            (params, opt_state), metrics = jax.lax.scan(
                body, (params, opt_state), batch, unroll=scan_unroll)
            last = jax.tree_util.tree_map(lambda m: m[-1], metrics)
            return params, opt_state, last

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0, 1))

    def place(params, opt_state):
        p_sh = (jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                       param_specs,
                                       is_leaf=lambda s: isinstance(s, P))
                if param_specs is not None else fsdp_sharding_tree(params, mesh))
        # Optimizer moments mirror the param shardings (matched by path, not
        # shape: e.g. wq/wo share a shape but carry transposed specs).
        o_sh = _opt_shardings(opt_state, params, p_sh, mesh)
        params = place_tree(mesh, params, p_sh)
        opt_state = place_tree(mesh, opt_state, o_sh)
        return params, opt_state

    bdim = 1 if steps_per_call > 1 else 0  # [K, B, ...] stacks shard on B

    def lift_spec(sh):
        """User-provided specs describe ONE step's batch; with a scanned
        step, prepend the (unsharded) steps dim."""
        if bdim == 0:
            return sh
        return NamedSharding(sh.mesh, P(None, *sh.spec))

    user_spec_tree = (jax.tree_util.tree_map(
        lift_spec, batch_spec_tree,
        is_leaf=lambda s: isinstance(s, NamedSharding))
        if batch_spec_tree is not None else None)

    def constrain(x):
        if user_spec_tree is not None:
            return jax.lax.with_sharding_constraint(x, user_spec_tree)
        dims = [None] * x.ndim
        if x.ndim > bdim:
            dims[bdim] = data_axes(mesh)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(*dims)))

    def sharded_step(params, opt_state, batch):
        batch = jax.tree_util.tree_map(constrain, batch)
        return step_fn(params, opt_state, batch)

    jitted = jax.jit(sharded_step, donate_argnums=(0, 1))
    jitted.place = place  # type: ignore[attr-defined]
    return jitted


def make_bn_train_step(loss_and_stats_fn, optimizer, mesh: Optional[Mesh] = None):
    """Train step for models with non-differentiable collection state (batch
    norm): gradients flow through ``params`` only; the extra state threads
    through as data.

    ``loss_and_stats_fn(params, batch_stats, batch) -> (loss,
    (new_batch_stats, metrics))``.  State dict: ``{"params", "batch_stats",
    "opt_state"}``.  With a mesh, ``step.place(state)`` gives params and
    optimizer moments FSDP placement when the mesh has an ``fsdp`` axis
    (replicated otherwise) and batch_stats replicated — the "ps role
    collapses into parameter sharding" mapping, for real.
    """
    import optax

    def step(state, batch):
        if mesh is not None:
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, batch_sharding(mesh)), batch)

        (loss, (batch_stats, metrics)), grads = jax.value_and_grad(
            loss_and_stats_fn, has_aux=True)(state["params"],
                                             state["batch_stats"], batch)
        updates, opt_state = optimizer.update(grads, state["opt_state"],
                                              state["params"])
        params = optax.apply_updates(state["params"], updates)
        out_metrics = dict(metrics)
        out_metrics["loss"] = loss
        return ({"params": params, "batch_stats": batch_stats,
                 "opt_state": opt_state}, out_metrics)

    jitted = jax.jit(step, donate_argnums=(0,))
    if mesh is not None:
        def place(state):
            p_sh = fsdp_sharding_tree(state["params"], mesh)
            o_sh = _opt_shardings(state["opt_state"], state["params"], p_sh,
                                  mesh)
            return {
                "params": place_tree(mesh, state["params"], p_sh),
                "batch_stats": place_tree(mesh, state["batch_stats"]),
                "opt_state": place_tree(mesh, state["opt_state"], o_sh),
            }
        jitted.place = place
    return jitted


def _opt_shardings(opt_state, params, param_shardings, mesh):
    """Sharding tree for an optax state: each moment leaf takes the sharding
    of the parameter whose pytree path is a suffix of the leaf's own path
    (optax moment trees — ``mu``/``nu`` etc. — mirror the params tree
    exactly, nested under state wrappers).  Scalars/counters replicate.
    Matching by path avoids aliasing distinct params that share a shape."""

    def path_key(path):
        return tuple(str(getattr(k, "key", getattr(k, "idx", getattr(k, "name", k))))
                     for k in path)

    p_leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    s_leaves = jax.tree_util.tree_leaves(
        param_shardings, is_leaf=lambda s: isinstance(s, NamedSharding))
    by_path = {path_key(path): (leaf.shape, sh)
               for (path, leaf), sh in zip(p_leaves, s_leaves)}
    replicated = NamedSharding(mesh, P())

    def assign(path, leaf):
        key = path_key(path)
        shape = getattr(leaf, "shape", ())
        for i in range(len(key)):
            hit = by_path.get(key[i:])
            if hit and hit[0] == shape:
                return hit[1]
        return replicated

    return jax.tree_util.tree_map_with_path(assign, opt_state)


def make_eval_step(loss_fn: Callable, mesh: Optional[Mesh] = None):
    """Jit'd forward-only step: ``loss_fn(params, batch) -> (loss,
    metrics)`` becomes ``eval_step(params, batch) -> metrics`` (loss
    included).  With a mesh, the batch is constrained onto the data axes
    like the train step's."""

    def step(params, batch):
        if mesh is not None:
            batch = jax.tree_util.tree_map(
                lambda x: jax.lax.with_sharding_constraint(
                    x, batch_sharding(mesh)), batch)
        loss, metrics = loss_fn(params, batch)
        out = dict(metrics)
        out["loss"] = loss
        return out

    return jax.jit(step)


def evaluate(eval_step: Callable, params, batches: Iterator,
             num_batches: int) -> Dict[str, float]:
    """Run ``num_batches`` eval steps and return the metric means — the
    validation half of the reference's trainers (mnist_replica.py:216-226
    evaluated once at the end; this is the reusable form)."""
    acc: Dict[str, list] = {}
    for _ in range(num_batches):
        # Keep device arrays: no host sync inside the loop, so batch N+1
        # dispatches while batch N still runs (matters on remote-attached
        # runtimes where each fetch is a full round-trip).
        for k, v in eval_step(params, next(batches)).items():
            acc.setdefault(k, []).append(v)
    return {k: float(sum(jnp.stack(vs)) / num_batches)
            for k, vs in acc.items()}


@dataclass
class TrainLoop:
    """Step loop with timing — the measurement point for the project metric
    (BASELINE.md: steps/sec/chip).

    ``metrics_path`` appends one JSON line per logged step
    (``{"step": N, "wall_s": ..., **metrics}``) — a machine-readable
    training curve with no dashboard dependency.

    ``checkpoint`` (a :class:`~tfmesos_tpu.train.checkpoint.
    CheckpointManager`) coordinates restart recovery: :meth:`resume`
    restores the latest saved ``TrainState`` (step offset included) before
    a run, and :meth:`run` saves every ``save_every`` global steps —
    ``save_async=True`` overlaps the Orbax write with the next steps.  The
    ``restores``/``resumed_step`` counters surface how a supervised job
    actually recovered (they ride the result dict too)."""

    step_fn: Callable
    state: TrainState
    log_every: int = 50
    name: str = "train"
    metrics_path: Optional[str] = None
    checkpoint: Optional[Any] = None
    save_every: int = 0
    save_async: bool = False
    restores: int = 0
    resumed_step: int = 0

    def state_dict(self) -> Dict[str, Any]:
        """The checkpointable form of ``state`` (also the restore
        template: leaves keep their shapes/dtypes/shardings)."""
        return {"params": self.state.params,
                "opt_state": self.state.opt_state,
                "step": jnp.asarray(self.state.step)}

    def resume(self) -> int:
        """Restore the latest checkpoint (if any) into ``state`` and
        return the step to resume from (0 on a cold start).  The caller
        owns realigning its batch iterator to that step — see
        ``supervisor.supervise_training`` for the stock skip-ahead."""
        if self.checkpoint is None:
            return 0
        restored = self.checkpoint.restore(self.state_dict())
        if restored is None:
            return 0
        self.state = TrainState(restored["params"], restored["opt_state"],
                                int(restored["step"]))
        self.restores += 1
        self.resumed_step = self.state.step
        log.info("%s resuming from checkpoint step %d", self.name,
                 self.state.step)
        return self.state.step

    def run(self, batches: Iterator[Dict[str, Any]], num_steps: int,
            on_metrics: Optional[Callable[[int, Dict], None]] = None) -> Dict[str, Any]:
        import json

        params, opt_state = self.state.params, self.state.opt_state
        t_start = time.perf_counter()
        metrics = {}
        sink = open(self.metrics_path, "a") if self.metrics_path else None

        def run_step(i):
            nonlocal params, opt_state, metrics
            batch = next(batches)
            params, opt_state, metrics = self.step_fn(params, opt_state, batch)
            gstep = self.state.step + i + 1
            if (self.checkpoint is not None and self.save_every
                    and gstep % self.save_every == 0):
                self.checkpoint.save(
                    gstep, {"params": params, "opt_state": opt_state,
                            "step": jnp.asarray(gstep)},
                    wait=not self.save_async)
            if (i + 1) % self.log_every == 0 or i + 1 == num_steps:
                metrics = {k: float(v) for k, v in metrics.items()}
                if sink:
                    sink.write(json.dumps(
                        {"step": gstep,
                         "wall_s": round(time.perf_counter() - t_start, 3),
                         **metrics}) + "\n")
                    sink.flush()
                if on_metrics:
                    on_metrics(i + 1, metrics)
                else:
                    log.info("%s step %d: %s", self.name, i + 1,
                             {k: round(v, 4) for k, v in metrics.items()})

        # Profile a bounded window, not the whole run: an unbounded trace of
        # a long job is multi-GB and unopenable.  No-op unless
        # TPUMESOS_TRACE_DIR is exported.
        import os
        traced = min(num_steps,
                     int(os.environ.get("TPUMESOS_TRACE_STEPS", "20")))
        try:
            with trace():
                for i in range(traced):
                    run_step(i)
            for i in range(traced, num_steps):
                run_step(i)
            jax.block_until_ready(params)
            if self.checkpoint is not None and self.save_async:
                self.checkpoint.wait_until_finished()
        finally:
            if sink:
                sink.close()
        elapsed = time.perf_counter() - t_start
        start_step = self.state.step
        self.state = TrainState(params, opt_state, start_step + num_steps)
        n_dev = max(1, jax.device_count())
        return {
            "elapsed_s": elapsed,
            "steps_per_sec": num_steps / elapsed,
            "steps_per_sec_per_chip": num_steps / elapsed / n_dev,
            "start_step": start_step,
            "final_step": self.state.step,
            "restores": self.restores,
            "resumed_step": self.resumed_step,
            "final_metrics": metrics,
        }
