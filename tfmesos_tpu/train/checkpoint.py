"""Checkpoint/resume via Orbax.

The reference has no framework-level checkpointing — its workloads lean on
``tf.train.Supervisor`` with a throwaway tempdir (mnist_replica.py:165-183,
SURVEY §5).  Here the framework plumbs a workdir and offers save/restore of
the whole TrainState; combined with the scheduler's fail-fast policy this
gives driver-level restart-from-checkpoint, the idiomatic TPU upgrade over
pretend-elastic PS recovery.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.checkpoint")


class CheckpointManager:
    def __init__(self, workdir: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.workdir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep,
                                                 create=True),
        )

    def save(self, step: int, state: Any, wait: bool = True) -> None:
        """Save ``state`` at ``step``.

        ``wait=False`` returns as soon as the on-device buffers are staged
        (Orbax writes asynchronously in the background), overlapping
        checkpoint IO with the next training steps; call
        :meth:`wait_until_finished` (or ``close``) before reading the
        checkpoint back or exiting.
        """
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        if wait:
            self._mgr.wait_until_finished()
        log.info("saved checkpoint step=%d at %s%s", step, self.workdir,
                 "" if wait else " (async)")

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, state_like: Any, step: Optional[int] = None) -> Any:
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            return None
        restored = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(state_like))
        # Copy every restored array: Orbax hands back arrays whose buffers
        # can alias checkpointer-internal memory, and DONATING one of
        # those to a jitted train step (donate_argnums — every step built
        # by trainer.make_train_step) intermittently corrupts the values
        # on this jax/orbax stack (observed as a resumed run silently
        # diverging from an uninterrupted one).  One defensive device
        # copy per leaf at restart time is noise next to the restart
        # itself; jnp.copy preserves shardings for mesh-restored arrays.
        import jax
        import jax.numpy as jnp
        restored = jax.tree_util.tree_map(
            lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x,
            restored)
        log.info("restored checkpoint step=%d", step)
        return restored

    def close(self) -> None:
        self._mgr.close()
