"""Driver-level restart supervision, checkpoint-coordinated.

The reference's failure story ends at fail-fast: any task death after
cluster start raises and tears everything down (scheduler.py:394-401), and
SURVEY §5 notes the idiomatic TPU upgrade is *not* pretend-elasticity (a TPU
mesh cannot hot-swap members mid-program) but automatic re-provision plus
restart from checkpoint.  This module is that upgrade, in two layers:

* :func:`supervise` — the bare restart loop: re-run an attempt function
  until success, retrying only :class:`ClusterError` (infrastructure
  death), never workload bugs.
* :func:`supervise_training` — the checkpoint-coordinated form: each
  attempt restores the latest :class:`~tfmesos_tpu.train.checkpoint.
  CheckpointManager` step into its :class:`~tfmesos_tpu.train.trainer.
  TrainLoop`, realigns the batch iterator to the resumed step (a pluggable
  skip-ahead hook — the default drains the iterator, seekable pipelines
  jump), runs only the remaining steps with periodic saves, and surfaces
  restart/resume counters.  Combined with the scheduler's
  ``restart_policy="elastic"`` (which re-forms the gang *under* the
  driver), this is the full story of docs/FAULT_TOLERANCE.md.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from tfmesos_tpu.scheduler import ClusterError, RemoteError
from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.supervisor")


@dataclass
class SuperviseResult:
    value: Any
    attempts: int
    elapsed_s: float


def supervise(run_attempt: Callable[[int], Any], max_restarts: int = 3,
              restart_wait: float = 5.0,
              should_retry: Optional[Callable[[BaseException], bool]] = None,
              ) -> SuperviseResult:
    """Run ``run_attempt(attempt_index)`` until it returns, restarting on
    cluster failure.

    ``run_attempt`` owns the whole attempt: bring up a cluster, restore the
    latest checkpoint, train, tear down (the ``cluster()`` context manager
    handles teardown even on failure).  Only :class:`ClusterError` — i.e.
    infrastructure death, the thing restarts can actually fix — triggers a
    retry by default; workload bugs propagate immediately, including
    exceptions raised by dispatched functions on tasks
    (:class:`RemoteError`).  ``should_retry`` widens/narrows that policy.
    """
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            value = run_attempt(attempt)
            return SuperviseResult(value=value, attempts=attempt + 1,
                                   elapsed_s=time.monotonic() - start)
        except BaseException as e:
            retry = (should_retry(e) if should_retry is not None
                     else isinstance(e, ClusterError)
                     and not isinstance(e, RemoteError))
            if not retry or attempt >= max_restarts:
                raise
            attempt += 1
            log.warning("attempt %d failed (%s: %s); restarting in %.1fs "
                        "(%d restart(s) left)", attempt, type(e).__name__, e,
                        restart_wait, max_restarts - attempt + 1)
            time.sleep(restart_wait)


# -- checkpoint-coordinated supervision -------------------------------------


def skip_batches(batches: Iterator, n: int) -> Iterator:
    """The stock batch-iterator skip-ahead: drain ``n`` batches so a
    resumed run sees exactly the data an uninterrupted one would have at
    the same step.  Correct for any iterator; O(resumed step).  Pipelines
    with seekable state (e.g. ``TokenFileDataset.batches(start_step=...)``)
    should plug their own hook and jump in O(1)."""
    for _ in range(n):
        next(batches)
    return batches


@dataclass
class TrainSuperviseResult:
    """What a supervised training run actually did."""

    result: Dict[str, Any]          # the final attempt's TrainLoop.run dict
    attempts: int                   # total attempts (1 = no restart)
    restarts: int                   # attempts - 1
    resumed_steps: List[int] = field(default_factory=list)  # per attempt
    elapsed_s: float = 0.0


def supervise_training(build: Callable[[int], Tuple[Any, Iterator]],
                       total_steps: int,
                       manager: Any,
                       save_every: int = 50,
                       max_restarts: int = 3,
                       restart_wait: float = 5.0,
                       skip_hook: Optional[Callable[[Iterator, int],
                                                    Iterator]] = skip_batches,
                       should_retry: Optional[Callable[[BaseException],
                                                       bool]] = None,
                       ) -> TrainSuperviseResult:
    """Run a training job to ``total_steps``, restarting on cluster
    failure and resuming each attempt from the latest checkpoint.

    ``build(attempt) -> (loop, batches)`` constructs a fresh
    :class:`~tfmesos_tpu.train.trainer.TrainLoop` (state initialized from
    scratch — the restore overwrites it) and its batch iterator, started
    from step 0.  Per attempt this supervisor: attaches ``manager`` to the
    loop, restores the latest saved step, realigns ``batches`` via
    ``skip_hook`` (pass ``None`` when ``build`` already starts the
    iterator at the resumed step — e.g. a seekable dataset reading
    ``manager.latest_step()`` itself), then runs only the remaining steps
    with a save every ``save_every`` global steps.

    Retry policy is :func:`supervise`'s: only :class:`ClusterError`
    restarts by default; workload bugs (and :class:`RemoteError`)
    propagate immediately.
    """
    if total_steps < 0:
        raise ValueError(f"total_steps must be >= 0, got {total_steps}")
    resumed_steps: List[int] = []

    def attempt(i: int) -> Dict[str, Any]:
        loop, batches = build(i)
        loop.checkpoint = manager
        loop.save_every = save_every
        start = loop.resume()
        resumed_steps.append(start)
        remaining = max(0, total_steps - start)
        if start and remaining and skip_hook is not None:
            batches = skip_hook(batches, start)
        return loop.run(batches, remaining)

    r = supervise(attempt, max_restarts=max_restarts,
                  restart_wait=restart_wait, should_retry=should_retry)
    return TrainSuperviseResult(result=r.value, attempts=r.attempts,
                                restarts=r.attempts - 1,
                                resumed_steps=resumed_steps,
                                elapsed_s=r.elapsed_s)
