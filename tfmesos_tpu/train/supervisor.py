"""Driver-level restart supervision.

The reference's failure story ends at fail-fast: any task death after
cluster start raises and tears everything down (scheduler.py:394-401), and
SURVEY §5 notes the idiomatic TPU upgrade is *not* pretend-elasticity (a TPU
mesh cannot hot-swap members mid-program) but automatic re-provision plus
restart from checkpoint.  This supervisor is that upgrade: it re-runs a
cluster bring-up + workload function until success, counting attempts, while
the workload checkpoints through :class:`~tfmesos_tpu.train.checkpoint.
CheckpointManager` and resumes from the latest step on each attempt.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from tfmesos_tpu.scheduler import ClusterError, RemoteError
from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.supervisor")


@dataclass
class SuperviseResult:
    value: Any
    attempts: int
    elapsed_s: float


def supervise(run_attempt: Callable[[int], Any], max_restarts: int = 3,
              restart_wait: float = 5.0,
              should_retry: Optional[Callable[[BaseException], bool]] = None,
              ) -> SuperviseResult:
    """Run ``run_attempt(attempt_index)`` until it returns, restarting on
    cluster failure.

    ``run_attempt`` owns the whole attempt: bring up a cluster, restore the
    latest checkpoint, train, tear down (the ``cluster()`` context manager
    handles teardown even on failure).  Only :class:`ClusterError` — i.e.
    infrastructure death, the thing restarts can actually fix — triggers a
    retry by default; workload bugs propagate immediately, including
    exceptions raised by dispatched functions on tasks
    (:class:`RemoteError`).  ``should_retry`` widens/narrows that policy.
    """
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            value = run_attempt(attempt)
            return SuperviseResult(value=value, attempts=attempt + 1,
                                   elapsed_s=time.monotonic() - start)
        except BaseException as e:
            retry = (should_retry(e) if should_retry is not None
                     else isinstance(e, ClusterError)
                     and not isinstance(e, RemoteError))
            if not retry or attempt >= max_restarts:
                raise
            attempt += 1
            log.warning("attempt %d failed (%s: %s); restarting in %.1fs "
                        "(%d restart(s) left)", attempt, type(e).__name__, e,
                        restart_wait, max_restarts - attempt + 1)
            time.sleep(restart_wait)
