"""Deterministic synthetic datasets.

This container has no network egress, so the reference's MNIST download
(mnist_replica's input_data.read_data_sets) is replaced by a seeded
generative MNIST stand-in: each class is a fixed random template in [0,1]^784
plus noise, which a 1-hidden-layer MLP separates at the same scale/difficulty
profile — giving a stable convergence gate (loss must fall, accuracy must
rise) without shipping data.  LM token streams for the transformer come from
a seeded Zipf-ish sampler.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator

import numpy as np


def _step_rng(seed: int, step: int) -> np.random.RandomState:
    """Independent RNG for (stream seed, step): seeding MT19937 with the
    pair (array seeds hash all entries) makes any step reachable in O(1) —
    resume never replays or regenerates skipped steps' draws."""
    return np.random.RandomState(
        np.array([seed & 0x7FFFFFFF, step], dtype=np.uint32))


@dataclass
class SyntheticMNIST:
    n_classes: int = 10
    dim: int = 784
    noise: float = 0.35
    seed: int = 0

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        self.templates = rng.rand(self.n_classes, self.dim).astype(np.float32)

    def batches(self, batch_size: int, seed: int = 1, start_step: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
        """``start_step`` starts the stream at that step: each batch is
        drawn from a per-step RNG (``_step_rng``), so a resumed run jumps
        straight to the checkpointed step in O(1) and sees exactly the
        batches a fresh run would from there — the data half of
        resume-from-checkpoint."""
        step = start_step
        while True:
            rng = _step_rng(seed, step)
            labels = rng.randint(0, self.n_classes, size=batch_size)
            images = self.templates[labels] + self.noise * rng.randn(
                batch_size, self.dim).astype(np.float32)
            yield {"image": np.clip(images, 0.0, 1.0).astype(np.float32),
                   "label": labels.astype(np.int32)}
            step += 1

    def eval_batch(self, batch_size: int = 1000, seed: int = 999):
        return next(self.batches(batch_size, seed=seed))


def token_batches(batch_size: int, seq_len: int, vocab_size: int,
                  seed: int = 0, start_step: int = 0
                  ) -> Iterator[Dict[str, np.ndarray]]:
    """Endless [B, T+1] token batches with mild structure (bigram-ish) so a
    language model has something learnable.  ``start_step`` jumps straight
    to that step (per-step RNG — see ``SyntheticMNIST.batches``)."""
    # Zipf-ish unigram distribution + deterministic successor bias; the
    # vocabulary structure comes from the base seed, not the step.
    ranks = np.arange(1, vocab_size + 1)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    successor = np.random.RandomState(seed).permutation(vocab_size)
    step = start_step
    while True:
        rng = _step_rng(seed, step)
        step += 1
        base = rng.choice(vocab_size, size=(batch_size, seq_len + 1), p=probs)
        # half the positions follow the deterministic successor of their
        # predecessor: learnable signal
        follow = rng.rand(batch_size, seq_len) < 0.5
        for t in range(1, seq_len + 1):
            base[:, t] = np.where(follow[:, t - 1], successor[base[:, t - 1]],
                                  base[:, t])
        yield {"tokens": base.astype(np.int32)}


def image_batches(batch_size: int, image_size: int, n_classes: int,
                  seed: int = 0, dataset_seed: int = 1234,
                  start_step: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic labeled images: class-dependent low-frequency pattern +
    noise (stands in for ImageNet in the vision trainers; no egress).

    ``dataset_seed`` fixes the class→pattern mapping; ``seed`` only drives
    the sample stream — so per-rank stream seeds decorrelate batches without
    giving each data-parallel worker a different definition of the classes
    (same split as SyntheticMNIST's templates vs batches)."""
    freqs = np.random.RandomState(dataset_seed).rand(n_classes, 2) * 4 + 1
    xs = np.linspace(0, np.pi, image_size, dtype=np.float32)
    grid_x, grid_y = np.meshgrid(xs, xs)
    step = start_step
    while True:
        rng = _step_rng(seed, step)
        step += 1
        labels = rng.randint(0, n_classes, size=batch_size)
        base = np.sin(freqs[labels, 0, None, None] * grid_x[None]) * \
            np.cos(freqs[labels, 1, None, None] * grid_y[None])
        images = base[..., None] + 0.3 * rng.randn(
            batch_size, image_size, image_size, 3).astype(np.float32)
        yield {"image": images.astype(np.float32),
               "label": labels.astype(np.int32)}


def nmf_matrix(rows: int, cols: int, rank: int, seed: int = 0) -> np.ndarray:
    """Ground-truth low-rank non-negative matrix (reference workload shape:
    matrix_factorization.py:53)."""
    rng = np.random.RandomState(seed)
    w = rng.rand(rows, rank).astype(np.float32)
    h = rng.rand(rank, cols).astype(np.float32)
    return w @ h / np.sqrt(rank)


def prefetch(batches: Iterator, mesh=None, depth: int = 2,
             batch_dim: int = 0) -> Iterator:
    """Overlap host->device transfer with compute.

    Wraps a host-side batch iterator: each batch is placed on the mesh (via
    :func:`~tfmesos_tpu.parallel.sharding.make_global_batch`, or plain
    ``device_put`` without a mesh) ``depth`` batches ahead of the consumer,
    so the copy engine streams the next inputs while the current step runs —
    the input-pipeline half of the reference's data story, which fed
    ``sess.run`` feeds synchronously (mnist_replica.py:198-210).
    """
    import collections

    import jax

    from tfmesos_tpu.parallel.sharding import make_global_batch

    def place(b):
        if mesh is None:
            return jax.tree_util.tree_map(jax.device_put, b)
        return make_global_batch(mesh, b, batch_dim=batch_dim)

    queue = collections.deque()
    for batch in batches:
        queue.append(place(batch))
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


@dataclass
class TokenFileDataset:
    """Memmap-backed token stream — the standard pretraining format: one
    flat on-disk array of token ids (uint16 for vocab <= 65536, else
    uint32), sampled as random [B, T+1] windows.

    Distributed reads shard by POSITION STRIPE: rank r of w samples only
    from its contiguous 1/w-th of the file, so hosts never touch the same
    pages (each host's page cache holds only its stripe) and streams stay
    decorrelated by construction rather than by seed luck.  The reference
    had no data story beyond each worker downloading MNIST for itself
    (mnist_replica.py:81); this is the TPU-native equivalent surface for
    real corpora on a shared filesystem.
    """

    path: str
    dtype: str = "uint16"
    seed: int = 0

    def __post_init__(self):
        self.tokens = np.memmap(self.path, dtype=np.dtype(self.dtype),
                                mode="r")
        if self.tokens.size < 2:
            raise ValueError(f"{self.path}: too few tokens "
                             f"({self.tokens.size})")

    @staticmethod
    def write(path: str, tokens: np.ndarray, dtype: str = "uint16") -> None:
        """Write a flat token array in this dataset's format."""
        np.asarray(tokens).astype(np.dtype(dtype)).tofile(path)

    def batches(self, batch_size: int, seq_len: int, rank: int = 0,
                world_size: int = 1, seed: int = None, start_step: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
        """Endless [B, T+1] next-token batches from this rank's stripe.

        ``start_step`` starts at that step for exact O(1) resume (per-step
        RNG; no skipped data is drawn or read)."""
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world of {world_size}")
        n = self.tokens.size
        lo = n * rank // world_size
        hi = n * (rank + 1) // world_size
        if hi - lo < seq_len + 1:
            raise ValueError(
                f"stripe [{lo}, {hi}) of {self.path} shorter than one "
                f"window ({seq_len + 1}); fewer ranks or a bigger file")
        base_seed = self.seed if seed is None else seed
        starts_max = hi - (seq_len + 1)
        step = start_step
        while True:
            starts = _step_rng(base_seed, step).randint(
                lo, starts_max + 1, size=batch_size)
            step += 1
            batch = np.stack([self.tokens[s:s + seq_len + 1] for s in starts])
            yield {"tokens": batch.astype(np.int32)}
