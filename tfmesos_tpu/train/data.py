"""Deterministic synthetic datasets.

This container has no network egress, so the reference's MNIST download
(mnist_replica's input_data.read_data_sets) is replaced by a seeded
generative MNIST stand-in: each class is a fixed random template in [0,1]^784
plus noise, which a 1-hidden-layer MLP separates at the same scale/difficulty
profile — giving a stable convergence gate (loss must fall, accuracy must
rise) without shipping data.  LM token streams for the transformer come from
a seeded Zipf-ish sampler.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Iterator

import numpy as np


def _step_rng(seed: int, step: int) -> np.random.RandomState:
    """Independent RNG for (stream seed, step): seeding MT19937 with the
    pair (array seeds hash all entries) makes any step reachable in O(1) —
    resume never replays or regenerates skipped steps' draws."""
    return np.random.RandomState(
        np.array([seed & 0x7FFFFFFF, step], dtype=np.uint32))


@dataclass
class SyntheticMNIST:
    n_classes: int = 10
    dim: int = 784
    noise: float = 0.35
    seed: int = 0

    def __post_init__(self):
        rng = np.random.RandomState(self.seed)
        self.templates = rng.rand(self.n_classes, self.dim).astype(np.float32)

    def batches(self, batch_size: int, seed: int = 1, start_step: int = 0
                ) -> Iterator[Dict[str, np.ndarray]]:
        """``start_step`` starts the stream at that step: each batch is
        drawn from a per-step RNG (``_step_rng``), so a resumed run jumps
        straight to the checkpointed step in O(1) and sees exactly the
        batches a fresh run would from there — the data half of
        resume-from-checkpoint."""
        step = start_step
        while True:
            rng = _step_rng(seed, step)
            labels = rng.randint(0, self.n_classes, size=batch_size)
            images = self.templates[labels] + self.noise * rng.randn(
                batch_size, self.dim).astype(np.float32)
            yield {"image": np.clip(images, 0.0, 1.0).astype(np.float32),
                   "label": labels.astype(np.int32)}
            step += 1

    def eval_batch(self, batch_size: int = 1000, seed: int = 999):
        return next(self.batches(batch_size, seed=seed))


def token_batches(batch_size: int, seq_len: int, vocab_size: int,
                  seed: int = 0, start_step: int = 0
                  ) -> Iterator[Dict[str, np.ndarray]]:
    """Endless [B, T+1] token batches with mild structure (bigram-ish) so a
    language model has something learnable.  ``start_step`` jumps straight
    to that step (per-step RNG — see ``SyntheticMNIST.batches``)."""
    # Zipf-ish unigram distribution + deterministic successor bias; the
    # vocabulary structure comes from the base seed, not the step.
    ranks = np.arange(1, vocab_size + 1)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    successor = np.random.RandomState(seed).permutation(vocab_size)
    step = start_step
    while True:
        rng = _step_rng(seed, step)
        step += 1
        base = rng.choice(vocab_size, size=(batch_size, seq_len + 1), p=probs)
        # half the positions follow the deterministic successor of their
        # predecessor: learnable signal
        follow = rng.rand(batch_size, seq_len) < 0.5
        for t in range(1, seq_len + 1):
            base[:, t] = np.where(follow[:, t - 1], successor[base[:, t - 1]],
                                  base[:, t])
        yield {"tokens": base.astype(np.int32)}


def image_batches(batch_size: int, image_size: int, n_classes: int,
                  seed: int = 0, dataset_seed: int = 1234,
                  start_step: int = 0) -> Iterator[Dict[str, np.ndarray]]:
    """Synthetic labeled images: class-dependent low-frequency pattern +
    noise (stands in for ImageNet in the vision trainers; no egress).

    ``dataset_seed`` fixes the class→pattern mapping; ``seed`` only drives
    the sample stream — so per-rank stream seeds decorrelate batches without
    giving each data-parallel worker a different definition of the classes
    (same split as SyntheticMNIST's templates vs batches)."""
    freqs = np.random.RandomState(dataset_seed).rand(n_classes, 2) * 4 + 1
    xs = np.linspace(0, np.pi, image_size, dtype=np.float32)
    grid_x, grid_y = np.meshgrid(xs, xs)
    step = start_step
    while True:
        rng = _step_rng(seed, step)
        step += 1
        labels = rng.randint(0, n_classes, size=batch_size)
        base = np.sin(freqs[labels, 0, None, None] * grid_x[None]) * \
            np.cos(freqs[labels, 1, None, None] * grid_y[None])
        images = base[..., None] + 0.3 * rng.randn(
            batch_size, image_size, image_size, 3).astype(np.float32)
        yield {"image": images.astype(np.float32),
               "label": labels.astype(np.int32)}


def nmf_matrix(rows: int, cols: int, rank: int, seed: int = 0) -> np.ndarray:
    """Ground-truth low-rank non-negative matrix (reference workload shape:
    matrix_factorization.py:53)."""
    rng = np.random.RandomState(seed)
    w = rng.rand(rows, rank).astype(np.float32)
    h = rng.rand(rank, cols).astype(np.float32)
    return w @ h / np.sqrt(rank)


def prefetch(batches: Iterator, mesh=None, depth: int = 2,
             batch_dim: int = 0) -> Iterator:
    """Overlap host->device transfer with compute.

    Wraps a host-side batch iterator: each batch is placed on the mesh (via
    :func:`~tfmesos_tpu.parallel.sharding.make_global_batch`, or plain
    ``device_put`` without a mesh) ``depth`` batches ahead of the consumer,
    so the copy engine streams the next inputs while the current step runs —
    the input-pipeline half of the reference's data story, which fed
    ``sess.run`` feeds synchronously (mnist_replica.py:198-210).
    """
    import collections

    import jax

    from tfmesos_tpu.parallel.sharding import make_global_batch

    def place(b):
        if mesh is None:
            return jax.tree_util.tree_map(jax.device_put, b)
        return make_global_batch(mesh, b, batch_dim=batch_dim)

    queue = collections.deque()
    for batch in batches:
        queue.append(place(batch))
        if len(queue) > depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()


class _NativeTokenGather:
    """ctypes wrapper over ``native/libtokenloader.so``: mmap + madvise
    gather/convert of [B, T+1] token windows in C++, optionally on a
    background thread (double-buffering against the train step).  Output
    is bit-identical to the numpy memmap path.  ``load()`` returns None
    when the library isn't built — callers fall back to numpy."""

    _lib = None
    _tried = False

    @classmethod
    def load(cls):
        if not cls._tried:
            cls._tried = True
            import ctypes
            path = os.path.join(os.path.dirname(__file__), os.pardir,
                                "native", "libtokenloader.so")
            if os.path.exists(path):
                try:
                    lib = ctypes.CDLL(path)
                    lib.tl_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
                    lib.tl_open.restype = ctypes.c_void_p
                    lib.tl_n_tokens.argtypes = [ctypes.c_void_p]
                    lib.tl_n_tokens.restype = ctypes.c_int64
                    ptr = ctypes.POINTER
                    args = [ctypes.c_void_p, ptr(ctypes.c_int64),
                            ctypes.c_int64, ctypes.c_int64,
                            ptr(ctypes.c_int32)]
                    lib.tl_gather.argtypes = args
                    lib.tl_gather.restype = ctypes.c_int
                    lib.tl_gather_async.argtypes = args
                    lib.tl_gather_async.restype = ctypes.c_int
                    lib.tl_wait.argtypes = [ctypes.c_void_p]
                    lib.tl_wait.restype = ctypes.c_int
                    lib.tl_close.argtypes = [ctypes.c_void_p]
                    lib.tl_close.restype = None
                    cls._lib = lib
                except OSError:
                    cls._lib = None
        return cls._lib

    def __init__(self, path: str, dtype: np.dtype):
        import ctypes
        self._ctypes = ctypes
        self.lib = self.load()
        if self.lib is None:
            raise RuntimeError("libtokenloader.so not built")
        self.handle = self.lib.tl_open(
            os.fsencode(os.path.abspath(path)), int(dtype.itemsize))
        if not self.handle:
            raise RuntimeError(f"tl_open failed for {path}")
        self.n_tokens = self.lib.tl_n_tokens(self.handle)
        # Keep the in-flight gather's operands alive until wait().
        self._inflight = None

    def _ptrs(self, starts: np.ndarray, out: np.ndarray):
        c = self._ctypes
        return (starts.ctypes.data_as(c.POINTER(c.c_int64)),
                len(starts), out.shape[1],
                out.ctypes.data_as(c.POINTER(c.c_int32)))

    def gather(self, starts: np.ndarray, t1: int) -> np.ndarray:
        starts = np.ascontiguousarray(starts, np.int64)
        out = np.empty((len(starts), t1), np.int32)
        rc = self.lib.tl_gather(self.handle, *self._ptrs(starts, out))
        if rc != 0:
            raise ValueError(f"tl_gather rc={rc}")
        return out

    def gather_async(self, starts: np.ndarray, t1: int) -> None:
        starts = np.ascontiguousarray(starts, np.int64)
        out = np.empty((len(starts), t1), np.int32)
        rc = self.lib.tl_gather_async(self.handle,
                                      *self._ptrs(starts, out))
        if rc != 0:
            raise ValueError(f"tl_gather_async rc={rc}")
        self._inflight = (starts, out)

    def wait(self) -> np.ndarray:
        starts, out = self._inflight
        self.lib.tl_wait(self.handle)
        self._inflight = None
        return out

    def close(self) -> None:
        if getattr(self, "handle", None):
            self.lib.tl_close(self.handle)
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


@dataclass
class TokenFileDataset:
    """Memmap-backed token stream — the standard pretraining format: one
    flat on-disk array of token ids (uint16 for vocab <= 65536, else
    uint32), sampled as random [B, T+1] windows.

    Distributed reads shard by POSITION STRIPE: rank r of w samples only
    from its contiguous 1/w-th of the file, so hosts never touch the same
    pages (each host's page cache holds only its stripe) and streams stay
    decorrelated by construction rather than by seed luck.  The reference
    had no data story beyond each worker downloading MNIST for itself
    (mnist_replica.py:81); this is the TPU-native equivalent surface for
    real corpora on a shared filesystem.
    """

    path: str
    dtype: str = "uint16"
    seed: int = 0

    def __post_init__(self):
        self.tokens = np.memmap(self.path, dtype=np.dtype(self.dtype),
                                mode="r")
        if self.tokens.size < 2:
            raise ValueError(f"{self.path}: too few tokens "
                             f"({self.tokens.size})")

    @staticmethod
    def write(path: str, tokens: np.ndarray, dtype: str = "uint16") -> None:
        """Write a flat token array in this dataset's format."""
        np.asarray(tokens).astype(np.dtype(dtype)).tofile(path)

    def batches(self, batch_size: int, seq_len: int, rank: int = 0,
                world_size: int = 1, seed: int = None, start_step: int = 0,
                native: bool = None) -> Iterator[Dict[str, np.ndarray]]:
        """Endless [B, T+1] next-token batches from this rank's stripe.

        ``start_step`` starts at that step for exact O(1) resume (per-step
        RNG; no skipped data is drawn or read).

        ``native=None`` auto-uses the C++ gather (``libtokenloader.so``)
        when built: the window copies + int32 convert run off the GIL with
        the NEXT step's batch assembling on a background thread while the
        current step trains — bit-identical output to the numpy path.
        ``False`` forces numpy; ``True`` errors if the library is missing.
        """
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world of {world_size}")
        n = self.tokens.size
        lo = n * rank // world_size
        hi = n * (rank + 1) // world_size
        if hi - lo < seq_len + 1:
            raise ValueError(
                f"stripe [{lo}, {hi}) of {self.path} shorter than one "
                f"window ({seq_len + 1}); fewer ranks or a bigger file")
        base_seed = self.seed if seed is None else seed
        starts_max = hi - (seq_len + 1)
        t1 = seq_len + 1

        def starts_for(step):
            return _step_rng(base_seed, step).randint(
                lo, starts_max + 1, size=batch_size)

        loader = None
        if native is not False:
            try:
                loader = _NativeTokenGather(self.path, np.dtype(self.dtype))
            except RuntimeError:
                if native:
                    raise
        if loader is None:
            step = start_step
            while True:
                starts = starts_for(step)
                step += 1
                batch = np.stack([self.tokens[s:s + t1] for s in starts])
                yield {"tokens": batch.astype(np.int32)}
        # Double-buffered native path: step N's gather overlapped with the
        # consumer's work on step N-1.  close() on GeneratorExit so an
        # abandoned iterator releases the mmap and joins the worker thread
        # deterministically, not at GC time.
        try:
            step = start_step
            loader.gather_async(starts_for(step), t1)
            while True:
                batch = loader.wait()
                step += 1
                loader.gather_async(starts_for(step), t1)
                yield {"tokens": batch}
        finally:
            loader.close()
