// Native token-file gather for TokenFileDataset (train/data.py).
//
// The Python hot loop builds each batch as B slice-copies off a memmap plus
// a uint16/uint32 -> int32 convert — all on the GIL, serialized with the
// step dispatch.  This library does the same gather+convert in C++ (madvise
// read-ahead, no GIL) and can run it on a background thread so batch N+1
// assembles while step N runs: the input-pipeline half of the runtime the
// reference delegated to TF's C++ input ops (SURVEY §2.6), rebuilt for the
// flat-token-file format.
//
// Contract (mirrors the numpy path bit for bit): out[i, :] =
// int32(tokens[starts[i] : starts[i] + t1]) for each of the b windows.
// One in-flight async gather per handle; tl_wait joins it.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>

namespace {

struct Loader {
  const uint8_t *base = nullptr;  // mmap'd file
  int64_t file_bytes = 0;
  int64_t n_tokens = 0;
  int dtype_bytes = 0;  // 2 (uint16) or 4 (uint32)
  std::thread worker;
  std::atomic<bool> busy{false};
};

void gather(const Loader *ld, const int64_t *starts, int64_t b, int64_t t1,
            int32_t *out) {
  const int64_t db = ld->dtype_bytes;
  const long page = sysconf(_SC_PAGESIZE);
  // Hint ALL windows before copying any: the kernel reads ahead for the
  // later rows while the earlier ones convert (hinting row i just before
  // copying row i would overlap with nothing).  Harmless when cached.
  for (int64_t i = 0; i < b; ++i) {
    const uint8_t *src = ld->base + starts[i] * db;
    const uintptr_t a0 = reinterpret_cast<uintptr_t>(src) & ~(page - 1);
    const uintptr_t a1 = reinterpret_cast<uintptr_t>(src + t1 * db);
    madvise(reinterpret_cast<void *>(a0), a1 - a0, MADV_WILLNEED);
  }
  for (int64_t i = 0; i < b; ++i) {
    const int64_t s = starts[i];
    const uint8_t *src = ld->base + s * db;
    int32_t *dst = out + i * t1;
    if (db == 2) {
      const uint16_t *p = reinterpret_cast<const uint16_t *>(src);
      for (int64_t j = 0; j < t1; ++j) dst[j] = static_cast<int32_t>(p[j]);
    } else {
      const uint32_t *p = reinterpret_cast<const uint32_t *>(src);
      for (int64_t j = 0; j < t1; ++j) dst[j] = static_cast<int32_t>(p[j]);
    }
  }
}

}  // namespace

extern "C" {

// Returns a handle (opaque pointer) or 0 on failure.
void *tl_open(const char *path, int dtype_bytes) {
  if (dtype_bytes != 2 && dtype_bytes != 4) return nullptr;
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    close(fd);
    return nullptr;
  }
  void *base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  close(fd);  // the mapping keeps the file alive
  if (base == MAP_FAILED) return nullptr;
  madvise(base, st.st_size, MADV_RANDOM);  // sampled windows, not a scan
  auto *ld = new Loader();
  ld->base = static_cast<const uint8_t *>(base);
  ld->file_bytes = st.st_size;
  ld->n_tokens = st.st_size / dtype_bytes;
  ld->dtype_bytes = dtype_bytes;
  return ld;
}

int64_t tl_n_tokens(void *handle) {
  return handle ? static_cast<Loader *>(handle)->n_tokens : -1;
}

// Synchronous gather: out must hold b*t1 int32s; every window must lie in
// [0, n_tokens - t1].  Returns 0 on success.
int tl_gather(void *handle, const int64_t *starts, int64_t b, int64_t t1,
              int32_t *out) {
  auto *ld = static_cast<Loader *>(handle);
  if (!ld || b <= 0 || t1 <= 0) return -1;
  for (int64_t i = 0; i < b; ++i)
    if (starts[i] < 0 || starts[i] + t1 > ld->n_tokens) return -2;
  gather(ld, starts, b, t1, out);
  return 0;
}

// Launch the same gather on a background thread.  starts/out must stay
// valid until tl_wait returns; one in-flight gather per handle.
int tl_gather_async(void *handle, const int64_t *starts, int64_t b,
                    int64_t t1, int32_t *out) {
  auto *ld = static_cast<Loader *>(handle);
  if (!ld || b <= 0 || t1 <= 0) return -1;
  if (ld->worker.joinable()) {
    // A finished-but-unjoined worker is still joinable; assigning over it
    // would std::terminate.  Only a gather actually mid-flight is an error.
    if (ld->busy.load()) return -3;
    ld->worker.join();
  }
  for (int64_t i = 0; i < b; ++i)
    if (starts[i] < 0 || starts[i] + t1 > ld->n_tokens) return -2;
  ld->busy.store(true);
  ld->worker = std::thread([ld, starts, b, t1, out] {
    gather(ld, starts, b, t1, out);
    ld->busy.store(false);
  });
  return 0;
}

// Join the in-flight gather (no-op when none).  Returns 0.
int tl_wait(void *handle) {
  auto *ld = static_cast<Loader *>(handle);
  if (!ld) return -1;
  if (ld->worker.joinable()) ld->worker.join();
  return 0;
}

void tl_close(void *handle) {
  auto *ld = static_cast<Loader *>(handle);
  if (!ld) return;
  if (ld->worker.joinable()) ld->worker.join();
  munmap(const_cast<uint8_t *>(ld->base), ld->file_bytes);
  delete ld;
}

}  // extern "C"
