// Native line pump for Mode-B child stdout (see tfmesos_tpu/logpump.py).
//
// Replaces the reference's per-line Python loop (server.py:99-102) with a
// splice loop in C++: read chunks from src_fd, mirror them verbatim to
// out_fd, and retransmit complete lines (prefixed) to fwd_fd.  Partial lines
// are buffered so the forwarded stream stays line-framed even when the child
// writes in arbitrary chunks.
//
// Build: `make -C tfmesos_tpu/native` → liblogpump.so (loaded via ctypes).

#include <cerrno>
#include <cstring>
#include <string>
#include <unistd.h>
#include <vector>

namespace {

// Write all of buf to fd, retrying on EINTR/partial writes.
// Returns false on unrecoverable error.
bool write_all(int fd, const char* buf, size_t len) {
  while (len > 0) {
    ssize_t n = ::write(fd, buf, len);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    buf += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

}  // namespace

extern "C" int tpumesos_pump_lines(int src_fd, int out_fd, int fwd_fd,
                                   const char* prefix, size_t prefix_len) {
  std::vector<char> chunk(1 << 16);
  std::string pending;  // partial line awaiting its newline, for forwarding
  bool fwd_ok = fwd_fd >= 0;

  for (;;) {
    ssize_t n = ::read(src_fd, chunk.data(), chunk.size());
    if (n < 0) {
      if (errno == EINTR) continue;
      return 1;
    }
    if (n == 0) break;  // EOF: child closed stdout

    if (!write_all(out_fd, chunk.data(), static_cast<size_t>(n))) return 1;

    if (!fwd_ok) continue;
    pending.append(chunk.data(), static_cast<size_t>(n));
    size_t start = 0;
    for (;;) {
      size_t nl = pending.find('\n', start);
      if (nl == std::string::npos) break;
      std::string line;
      line.reserve(prefix_len + (nl - start) + 1);
      line.append(prefix, prefix_len);
      line.append(pending, start, nl - start + 1);
      if (!write_all(fwd_fd, line.data(), line.size())) {
        fwd_ok = false;  // collector went away; keep local mirroring alive
        break;
      }
      start = nl + 1;
    }
    pending.erase(0, start);
  }

  // Forward any trailing unterminated line.
  if (fwd_ok && !pending.empty()) {
    std::string line;
    line.append(prefix, prefix_len);
    line.append(pending);
    write_all(fwd_fd, line.data(), line.size());
  }
  return 0;
}
