"""Task-side runtime: env contract, distributed init, mesh handles.

The reference exports ``TFMESOS_*`` env vars to between-graph user programs
(server.py:76-84) which then build their own ``tf.train.ClusterSpec``.  The
TPU-native contract keeps those names for drop-in compatibility and adds the
``TPUMESOS_*`` set carrying what a ``jax.distributed`` process actually
needs: rank, world size, coordinator address, and mesh axes.  A user program
calls :func:`initialize` once and gets a :class:`TaskContext` whose
``mesh()`` replaces the reference's ``ClusterSpec``+``tf.train.Server``
bring-up (mnist_replica.py:85-90) entirely.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

ENV_RANK = "TPUMESOS_RANK"
ENV_WORLD = "TPUMESOS_WORLD_SIZE"
ENV_COORDINATOR = "TPUMESOS_COORDINATOR"
ENV_CLUSTER_DEF = "TPUMESOS_CLUSTER_DEF"
ENV_JOB_NAME = "TPUMESOS_JOB_NAME"
ENV_TASK_INDEX = "TPUMESOS_TASK_INDEX"
ENV_MESH_AXES = "TPUMESOS_MESH_AXES"

_initialized = False


@dataclass
class TaskContext:
    """Everything one cluster member knows about itself and its peers."""

    rank: int = 0
    world_size: int = 1
    job_name: str = "worker"
    task_index: int = 0
    coordinator: Optional[str] = None
    cluster_def: Dict[str, List[str]] = field(default_factory=dict)
    mesh_axes: Optional[Dict[str, int]] = None
    extra_config: Dict[str, Any] = field(default_factory=dict)

    @property
    def is_chief(self) -> bool:
        return self.rank == 0

    def mesh(self, axes: Optional[Dict[str, int]] = None):
        """Build a ``jax.sharding.Mesh`` over all global devices.

        This is the successor of the reference's ``.targets`` map
        (scheduler.py:279-286): instead of per-task gRPC session targets, user
        code gets one mesh handle and lets shardings decide placement.
        """
        from tfmesos_tpu.parallel.mesh import build_mesh
        return build_mesh(axes or self.mesh_axes)

    @classmethod
    def from_env(cls) -> "TaskContext":
        cluster_def = json.loads(os.environ.get(ENV_CLUSTER_DEF, "{}"))
        mesh_axes_raw = os.environ.get(ENV_MESH_AXES, "")
        return cls(
            rank=int(os.environ.get(ENV_RANK, "0")),
            world_size=int(os.environ.get(ENV_WORLD, "1")),
            job_name=os.environ.get(ENV_JOB_NAME, "worker"),
            task_index=int(os.environ.get(ENV_TASK_INDEX, "0")),
            coordinator=os.environ.get(ENV_COORDINATOR) or None,
            cluster_def=cluster_def,
            mesh_axes=json.loads(mesh_axes_raw) if mesh_axes_raw else None,
        )

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "TaskContext":
        return cls(
            rank=int(config.get("rank", 0)),
            world_size=int(config.get("world_size", 1)),
            job_name=config.get("job_name", "worker"),
            task_index=int(config.get("task_index", 0)),
            coordinator=config.get("coordinator"),
            cluster_def=config.get("cluster_def") or {},
            mesh_axes=config.get("mesh_axes"),
            extra_config=config.get("extra_config") or {},
        )


def task_env(config: Dict[str, Any]) -> Dict[str, str]:
    """Render the env-var contract for a task config (both the compatible
    ``TFMESOS_*`` set, reference server.py:76-84, and the new ``TPUMESOS_*``
    set)."""
    cluster_def = config.get("cluster_def") or {}
    env = {
        # Reference-compatible set (hard-coded ps/worker names as in
        # server.py:72-75; empty when those jobs don't exist).
        "TFMESOS_PS_HOSTS": ",".join(cluster_def.get("ps", [])),
        "TFMESOS_WORKER_HOSTS": ",".join(cluster_def.get("worker", [])),
        "TFMESOS_JOB_NAME": str(config.get("job_name", "")),
        "TFMESOS_TASK_INDEX": str(config.get("task_index", 0)),
        "TFMESOS_DISTRIBUTED": "1",
        # TPU-native set.
        ENV_RANK: str(config.get("rank", 0)),
        ENV_WORLD: str(config.get("world_size", 1)),
        ENV_JOB_NAME: str(config.get("job_name", "")),
        ENV_TASK_INDEX: str(config.get("task_index", 0)),
        ENV_CLUSTER_DEF: json.dumps(cluster_def, separators=(",", ":")),
        "PYTHONUNBUFFERED": "1",
    }
    if config.get("coordinator"):
        env[ENV_COORDINATOR] = config["coordinator"]
    if config.get("mesh_axes"):
        env[ENV_MESH_AXES] = json.dumps(config["mesh_axes"], separators=(",", ":"))
    return env


def initialize(ctx: Optional[TaskContext] = None) -> TaskContext:
    """Join the distributed runtime.

    Replaces the reference's ``tf.train.Server(ServerDef).join()`` bring-up
    (server.py:52-66): one call wires this process into the global XLA
    runtime; afterwards ``jax.devices()`` sees every chip in the slice and
    collectives ride ICI.  Safe to call in a single-process run (no-op).
    """
    global _initialized
    if ctx is None:
        ctx = TaskContext.from_env()
    # Make the env var authoritative even when a site-installed PJRT plugin
    # pre-set the platform via jax.config at interpreter start (config beats
    # JAX_PLATFORMS; without this a multi-process CPU cluster silently falls
    # apart into single-device processes).
    from tfmesos_tpu.utils.platform import force_platform
    force_platform()
    import jax
    if ctx.world_size > 1 and not _initialized:
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator,
            num_processes=ctx.world_size,
            process_id=ctx.rank,
        )
        _initialized = True
    # force_platform is best-effort (a plugin that already initialized a
    # backend wins silently) — verify, because proceeding on the wrong
    # platform is exactly the silent degradation this guard exists to stop.
    # Checked only after distributed init: querying devices earlier would
    # initialize the local backend and break jax.distributed.
    requested = os.environ.get("JAX_PLATFORMS")
    if requested:
        allowed = [p.strip() for p in requested.split(",") if p.strip()]
        got = jax.local_devices()[0].platform
        if got not in allowed:
            raise RuntimeError(
                f"JAX_PLATFORMS={requested} was requested but the backend "
                f"initialized as {got!r} — a site PJRT plugin pinned the "
                "platform before runtime.initialize() ran")
    return ctx
