"""Non-negative matrix factorization (reference examples/matrix_factorization.py).

The reference pins factor W on ps:0 and H on ps:1 by hand
(matrix_factorization.py:21-28) — explicit model parallelism.  Here the
factors are sharded over the mesh with PartitionSpecs (W by rows, H by
columns) and the update is one jit'd gradient step; XLA inserts the
collectives that the manual device placement used to imply.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


@dataclass(frozen=True)
class NMFConfig:
    rows: int = 1000       # reference workload: 1000x1000 (m_f.py:53)
    cols: int = 1000
    rank: int = 200        # reference rank 200
    dtype: Any = jnp.float32


def init_params(cfg: NMFConfig, rng) -> Dict[str, Any]:
    kw, kh = jax.random.split(rng)
    return {
        "W": jax.random.uniform(kw, (cfg.rows, cfg.rank), cfg.dtype),
        "H": jax.random.uniform(kh, (cfg.rank, cfg.cols), cfg.dtype),
    }


def partition_specs(cfg: NMFConfig, mesh: Mesh) -> Dict[str, P]:
    """W sharded by rows, H by columns over the first non-trivial mesh axis —
    the GSPMD form of the reference's per-ps-task factor placement."""
    axis = next((a for a in ("fsdp", "dp", "tp") if mesh.shape.get(a, 1) > 1),
                None)
    return {"W": P(axis, None), "H": P(None, axis)}


def loss_fn(cfg: NMFConfig, params, batch, mesh=None):
    v = batch["V"]
    approx = params["W"] @ params["H"]
    err = v - approx
    return jnp.mean(err * err), {"err_mean": jnp.mean(jnp.abs(err))}


def project_nonnegative(params):
    """NMF constraint: clamp factors at zero after each gradient step."""
    return jax.tree_util.tree_map(lambda p: jnp.maximum(p, 0.0), params)
