"""Inception-v3 (BASELINE.json config: "Inception-v3 distributed_train,
4 ps + 8 worker → 8-chip mesh").

The original distributed_train placed variables on 4 parameter servers and
replicated the tower over 8 workers; here the tower is one flax module and
the "4 ps" role is FSDP parameter sharding over the 8-chip mesh (north-star
mapping, SURVEY §2.7).  NHWC, bf16 compute, fp32 BN stats, optional aux
head as in the original.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfmesos_tpu.ops.layers import cross_entropy_loss


@dataclass(frozen=True)
class InceptionConfig:
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    image_size: int = 299
    width_mult: float = 1.0     # scales every channel count (tiny variants)
    aux_head: bool = True

    def ch(self, n: int) -> int:
        return max(8, int(n * self.width_mult))

    @staticmethod
    def tiny():
        return InceptionConfig(num_classes=10, dtype=jnp.float32,
                               image_size=75, width_mult=0.125,
                               aux_head=False)


class BasicConv(nn.Module):
    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=False, dtype=self.dtype,
                    param_dtype=jnp.float32)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype,
                         param_dtype=jnp.float32)(x)
        return nn.relu(x)


def _pool(x, kind: str):
    if kind == "max":
        return nn.max_pool(x, (3, 3), strides=(1, 1), padding="SAME")
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    cfg: InceptionConfig
    pool_features: int

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(BasicConv, dtype=self.cfg.dtype)
        ch = self.cfg.ch
        b1 = c(ch(64), (1, 1))(x, train)
        b2 = c(ch(48), (1, 1))(x, train)
        b2 = c(ch(64), (5, 5))(b2, train)
        b3 = c(ch(64), (1, 1))(x, train)
        b3 = c(ch(96), (3, 3))(b3, train)
        b3 = c(ch(96), (3, 3))(b3, train)
        b4 = c(self.cfg.ch(self.pool_features), (1, 1))(_pool(x, "avg"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):  # grid reduction 35 -> 17
    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(BasicConv, dtype=self.cfg.dtype)
        ch = self.cfg.ch
        b1 = c(ch(384), (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(ch(64), (1, 1))(x, train)
        b2 = c(ch(96), (3, 3))(b2, train)
        b2 = c(ch(96), (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    cfg: InceptionConfig
    c7: int

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(BasicConv, dtype=self.cfg.dtype)
        ch, c7 = self.cfg.ch, self.cfg.ch(self.c7)
        b1 = c(ch(192), (1, 1))(x, train)
        b2 = c(c7, (1, 1))(x, train)
        b2 = c(c7, (1, 7))(b2, train)
        b2 = c(ch(192), (7, 1))(b2, train)
        b3 = c(c7, (1, 1))(x, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(c7, (1, 7))(b3, train)
        b3 = c(c7, (7, 1))(b3, train)
        b3 = c(ch(192), (1, 7))(b3, train)
        b4 = c(ch(192), (1, 1))(_pool(x, "avg"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):  # grid reduction 17 -> 8
    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(BasicConv, dtype=self.cfg.dtype)
        ch = self.cfg.ch
        b1 = c(ch(192), (1, 1))(x, train)
        b1 = c(ch(320), (3, 3), strides=(2, 2), padding="VALID")(b1, train)
        b2 = c(ch(192), (1, 1))(x, train)
        b2 = c(ch(192), (1, 7))(b2, train)
        b2 = c(ch(192), (7, 1))(b2, train)
        b2 = c(ch(192), (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool):
        c = partial(BasicConv, dtype=self.cfg.dtype)
        ch = self.cfg.ch
        b1 = c(ch(320), (1, 1))(x, train)
        b2 = c(ch(384), (1, 1))(x, train)
        b2 = jnp.concatenate([c(ch(384), (1, 3))(b2, train),
                              c(ch(384), (3, 1))(b2, train)], axis=-1)
        b3 = c(ch(448), (1, 1))(x, train)
        b3 = c(ch(384), (3, 3))(b3, train)
        b3 = jnp.concatenate([c(ch(384), (1, 3))(b3, train),
                              c(ch(384), (3, 1))(b3, train)], axis=-1)
        b4 = c(ch(192), (1, 1))(_pool(x, "avg"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    cfg: InceptionConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        c = partial(BasicConv, dtype=cfg.dtype)
        ch = cfg.ch
        x = x.astype(cfg.dtype)
        # Stem
        x = c(ch(32), (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(ch(32), (3, 3), padding="VALID")(x, train)
        x = c(ch(64), (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = c(ch(80), (1, 1))(x, train)
        x = c(ch(192), (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        # Inception stacks
        for pool_features in (32, 64, 64):
            x = InceptionA(cfg, pool_features)(x, train)
        x = InceptionB(cfg)(x, train)
        aux = None
        for c7 in (128, 160, 160, 192):
            x = InceptionC(cfg, c7)(x, train)
        if cfg.aux_head:
            a = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
            a = c(ch(128), (1, 1))(a, train)
            a = c(ch(768), (5, 5), padding="VALID")(a, train)
            a = jnp.mean(a, axis=(1, 2))
            aux = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                           param_dtype=jnp.float32, name="aux_logits")(a)
        x = InceptionD(cfg)(x, train)
        x = InceptionE(cfg)(x, train)
        x = InceptionE(cfg)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="logits")(x)
        return (logits, aux) if cfg.aux_head else logits


def init_params(cfg: InceptionConfig, rng):
    model = InceptionV3(cfg)
    dummy = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return {"params": variables["params"],
            "batch_stats": variables["batch_stats"]}


def make_train_step(cfg: InceptionConfig, optimizer, mesh=None,
                    aux_weight: float = 0.4):
    """Train step with the original's auxiliary-classifier loss (weight 0.4)
    via the shared BN-aware builder; FSDP param placement when the mesh has
    an ``fsdp`` axis (the "4 ps" role, for real — call ``step.place``)."""
    from tfmesos_tpu.train.trainer import make_bn_train_step

    model = InceptionV3(cfg)

    def loss_and_stats(params, batch_stats, batch):
        out, updated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"], train=True, mutable=["batch_stats"])
        logits, aux = out if cfg.aux_head else (out, None)
        loss = cross_entropy_loss(logits, batch["label"])
        if aux is not None:
            loss = loss + aux_weight * cross_entropy_loss(aux, batch["label"])
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"])
                       .astype(jnp.float32))
        return loss, (updated["batch_stats"], {"accuracy": acc})

    return make_bn_train_step(loss_and_stats, optimizer, mesh=mesh)


def eval_logits(cfg: InceptionConfig, state, images):
    out = InceptionV3(cfg).apply(
        {"params": state["params"], "batch_stats": state["batch_stats"]},
        images, train=False)
    return out[0] if cfg.aux_head else out
