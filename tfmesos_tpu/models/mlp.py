"""The mnist_replica workload model (reference examples/mnist/mnist_replica.py).

Same architecture scale as the reference trainer — one hidden layer
(default 100 units, mnist_replica.py:70-73), softmax cross entropy — built
as a pure-jax functional model whose gradients sync over the mesh instead of
flowing through parameter servers.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from tfmesos_tpu.ops.layers import cross_entropy_loss


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: int = 100      # reference default (mnist_replica.py:70)
    n_classes: int = 10
    dtype: Any = jnp.float32


def init_params(cfg: MLPConfig, rng) -> Dict[str, Any]:
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (cfg.in_dim, cfg.hidden), cfg.dtype)
        / jnp.sqrt(cfg.in_dim),
        "b1": jnp.zeros((cfg.hidden,), cfg.dtype),
        "w2": jax.random.normal(k2, (cfg.hidden, cfg.n_classes), cfg.dtype)
        / jnp.sqrt(cfg.hidden),
        "b2": jnp.zeros((cfg.n_classes,), cfg.dtype),
    }


def forward(cfg: MLPConfig, params, x):
    h = jax.nn.relu(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def loss_fn(cfg: MLPConfig, params, batch, mesh=None):
    logits = forward(cfg, params, batch["image"])
    loss = cross_entropy_loss(logits, batch["label"])
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"]).astype(jnp.float32))
    return loss, {"accuracy": acc}
