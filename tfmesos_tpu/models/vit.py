"""Vision Transformer (ViT-B/16 shape by default), TPU-first.

Beyond the reference's model zoo (its largest vision nets are the ResNet-50
and Inception-v3 re-dos; SURVEY §2.5) — a ViT rounds out the families on
the architecture TPUs run best: patchify is one conv (MXU), the trunk is
the same pre-norm attention/MLP stack as the flagship language model (the
flash kernel applies unchanged since patch counts tile cleanly), and
everything shards with the same tp/fsdp PartitionSpec vocabulary.

Plain-jnp parameter dict like models/transformer.py (no framework module
state — no batch norm anywhere), so the generic ``make_train_step`` works
as-is with FSDP default shardings.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp

from tfmesos_tpu.ops.attention import flash_attention
from tfmesos_tpu.ops.layers import cross_entropy_loss


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def tiny():
        """Test-scale variant (same code path)."""
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         d_model=32, n_layers=2, n_heads=2, d_ff=64,
                         dtype=jnp.float32)


def init_params(cfg: ViTConfig, rng) -> Dict[str, Any]:
    d, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
    p = cfg.patch_size
    keys = iter(jax.random.split(rng, 12))

    def norm(shape, scale):
        return (jax.random.normal(next(keys), shape, cfg.param_dtype)
                * scale).astype(cfg.param_dtype)

    return {
        # patchify = one dense over flattened p*p*3 pixels (== conv stride p)
        "patch_w": norm((p * p * 3, d), 1 / math.sqrt(p * p * 3)),
        "patch_b": jnp.zeros((d,), cfg.param_dtype),
        "pos_embed": norm((cfg.n_patches + 1, d), 0.02),
        "cls": jnp.zeros((d,), cfg.param_dtype),
        "layers": {
            "norm1": jnp.ones((l, d), cfg.param_dtype),
            "wq": norm((l, d, d), 1 / math.sqrt(d)),
            "wk": norm((l, d, d), 1 / math.sqrt(d)),
            "wv": norm((l, d, d), 1 / math.sqrt(d)),
            "wo": norm((l, d, d), 1 / math.sqrt(d) / math.sqrt(2 * l)),
            "norm2": jnp.ones((l, d), cfg.param_dtype),
            "w1": norm((l, d, f), 1 / math.sqrt(d)),
            "b1": jnp.zeros((l, f), cfg.param_dtype),
            "w2": norm((l, f, d), 1 / math.sqrt(f) / math.sqrt(2 * l)),
            "b2": jnp.zeros((l, d), cfg.param_dtype),
        },
        "norm_f": jnp.ones((d,), cfg.param_dtype),
        "head_w": norm((d, cfg.num_classes), 1 / math.sqrt(d)),
        "head_b": jnp.zeros((cfg.num_classes,), cfg.param_dtype),
    }


def _layer_norm(x, w):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * w


def _block(cfg: ViTConfig, x, lp):
    b, t, d = x.shape
    h = _layer_norm(x, lp["norm1"].astype(cfg.dtype))
    q = (h @ lp["wq"].astype(cfg.dtype)).reshape(b, t, cfg.n_heads,
                                                 cfg.head_dim)
    k = (h @ lp["wk"].astype(cfg.dtype)).reshape(b, t, cfg.n_heads,
                                                 cfg.head_dim)
    v = (h @ lp["wv"].astype(cfg.dtype)).reshape(b, t, cfg.n_heads,
                                                 cfg.head_dim)
    o = flash_attention(q, k, v, causal=False)
    x = x + o.reshape(b, t, d) @ lp["wo"].astype(cfg.dtype)
    h = _layer_norm(x, lp["norm2"].astype(cfg.dtype))
    h = jax.nn.gelu(h @ lp["w1"].astype(cfg.dtype)
                    + lp["b1"].astype(cfg.dtype))
    return x + h @ lp["w2"].astype(cfg.dtype) + lp["b2"].astype(cfg.dtype)


def forward(cfg: ViTConfig, params, images):
    """images [B, H, W, 3] (NHWC) -> logits [B, num_classes]."""
    b = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.astype(cfg.dtype).reshape(b, g, p, g, p, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, g * g, p * p * 3)
    x = x @ params["patch_w"].astype(cfg.dtype) \
        + params["patch_b"].astype(cfg.dtype)
    cls = jnp.broadcast_to(params["cls"].astype(cfg.dtype), (b, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos_embed"].astype(cfg.dtype)

    def body(carry, lp):
        return _block(cfg, carry, lp), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _layer_norm(x[:, 0], params["norm_f"].astype(cfg.dtype))
    return x @ params["head_w"].astype(cfg.dtype) \
        + params["head_b"].astype(cfg.dtype)


def loss_fn(cfg: ViTConfig, params, batch, mesh=None):
    logits = forward(cfg, params, batch["image"])
    loss = cross_entropy_loss(logits, batch["label"])
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"])
                   .astype(jnp.float32))
    return loss, {"accuracy": acc}
