"""ResNet-50 (BASELINE.json config: "ResNet-50 ImageNet sync-SGD, no PS,
pure ICI all-reduce").

flax.linen implementation, TPU-first: NHWC layout (XLA's native conv layout
on TPU), bf16 compute with fp32 batch-norm statistics, bottleneck v1.5
(stride in the 3x3).  Data parallelism comes from the trainer's mesh — there
is no PS variant, matching the BASELINE config's "no PS" phrasing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfmesos_tpu.ops.layers import cross_entropy_loss


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)  # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    image_size: int = 224

    @staticmethod
    def tiny():
        """Test-scale variant (same code path, minutes→seconds)."""
        return ResNetConfig(num_classes=10, stage_sizes=(1, 1), width=8,
                            image_size=32, dtype=jnp.float32)


class Bottleneck(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, dtype=self.dtype, param_dtype=jnp.float32)
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            strides=(self.strides, self.strides),
                            name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=cfg.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 dtype=cfg.dtype,
                                 param_dtype=jnp.float32)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(cfg.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = Bottleneck(cfg.width * 2 ** i, strides, cfg.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


def init_params(cfg: ResNetConfig, rng):
    model = ResNet(cfg)
    dummy = jnp.zeros((1, cfg.image_size, cfg.image_size, 3), jnp.float32)
    variables = model.init(rng, dummy, train=False)
    return {"params": variables["params"],
            "batch_stats": variables["batch_stats"]}


def make_train_step(cfg: ResNetConfig, optimizer, mesh=None):
    """BatchNorm-aware train step via the shared builder: gradients through
    ``params`` only, batch_stats threaded as state, FSDP param placement
    when the mesh has an ``fsdp`` axis (call ``step.place(state)`` once)."""
    from tfmesos_tpu.train.trainer import make_bn_train_step

    model = ResNet(cfg)

    def loss_and_stats(params, batch_stats, batch):
        logits, updated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            batch["image"], train=True, mutable=["batch_stats"])
        loss = cross_entropy_loss(logits, batch["label"])
        acc = jnp.mean((jnp.argmax(logits, -1) == batch["label"])
                       .astype(jnp.float32))
        return loss, (updated["batch_stats"], {"accuracy": acc})

    return make_bn_train_step(loss_and_stats, optimizer, mesh=mesh)


def eval_logits(cfg: ResNetConfig, state, images):
    return ResNet(cfg).apply(
        {"params": state["params"], "batch_stats": state["batch_stats"]},
        images, train=False)
