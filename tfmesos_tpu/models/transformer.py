"""Flagship model: decoder-only transformer, TPU-first.

Nothing like this exists in the reference (its largest workload is a
2-layer MLP, SURVEY §2.5) — this is the model family that exercises every
mesh axis the framework offers:

* ``dp``/``fsdp`` — batch sharding + FSDP parameter sharding (the GSPMD
  successor of parameter servers),
* ``tp`` — Megatron-style tensor parallelism (heads/ff sharded, vocab-
  parallel embedding/head),
* ``sp`` — ring attention over the sequence (parallel/ring_attention.py),
* ``pp`` — pipeline stages over layer groups (parallel/pipeline.py),
* ``ep`` — expert-parallel MoE blocks.

Design choices for the MXU/XLA: stacked per-layer parameters consumed by
``lax.scan`` (one compiled block, L iterations), bf16 compute with fp32
master params and fp32 softmax/normalization accumulation, static shapes
throughout, optional ``jax.checkpoint`` rematerialization.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfmesos_tpu.compat import axis_size, shard_map
from tfmesos_tpu.ops.attention import attend, mha_reference
from tfmesos_tpu.ops.layers import (cross_entropy_loss,
                                    data_parallel_fused_cross_entropy,
                                    fused_linear_cross_entropy, rms_norm,
                                    vocab_parallel_ce_inbody,
                                    rope,
                                    vocab_parallel_cross_entropy)
from tfmesos_tpu.ops.quant import QTensor, quantize_tensor


def _wt(p, dtype):
    """Weight-on-use: dequantize an int8 :class:`QTensor` or cast a plain
    array to the compute dtype.  Matmul call sites should prefer
    :func:`_qmm` — round-5 chip measurement showed XLA materializing the
    scale*convert product from this form instead of fusing it into the
    dot, costing MORE bandwidth than bf16 weights; kept for the einsum
    sites (MoE experts) where the activation fold does not apply
    directly."""
    if isinstance(p, QTensor):
        return p.dequantize(dtype)
    return p.astype(dtype)


def _qmm(x, p, dtype):
    """``x @ W`` for a plain or int8 weight.  A QTensor's per-input-
    channel scales ([K, 1], K the contraction dim) commute across the
    dot, so they fold into the (tiny) activation — ``(x * s) @ values``
    — and the remaining pure int8->dtype convert DOES fuse into the
    matmul, leaving HBM reading the int8 bytes only.  Measured on a v5e
    chip at decode shapes (M=8, K=N=2048): 0.14 ms vs 0.34 ms for
    ``x @ dequantize(W)`` and 0.36 ms for bf16 weights — the form that
    makes int8 weights actually FASTER than bf16, not just smaller."""
    if isinstance(p, QTensor):
        s = p.scales.reshape(p.scales.shape[:-2] + (-1,)).astype(dtype)
        return (x * s) @ p.values.astype(dtype)
    return x @ p.astype(dtype)


def _embed_lookup(p, tokens, dtype):
    """Embedding gather for plain or quantized tables: gather int8 rows and
    their scales, then dequantize only the gathered rows."""
    if isinstance(p, QTensor):
        return (p.values[tokens].astype(dtype)
                * p.scales[tokens].astype(dtype))
    return p.astype(dtype)[tokens]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    d_ff: int = 2048
    # Grouped-query attention: n_kv_heads < n_heads shares each K/V head
    # across n_heads/n_kv_heads query heads — exact attention with a
    # KV cache (and wk/wv) smaller by that factor, the standard serving
    # memory/bandwidth win.  None = full multi-head attention.
    n_kv_heads: Optional[int] = None
    max_seq_len: int = 2048
    # Sliding-window attention (Mistral-style): each query sees the last
    # `window` positions only.  None = full causal attention.  The flash
    # kernel bounds its k-loop to the window (O(T·W) work); decode masks
    # cache reads the same way.  Does not compose with sp (ring/Ulysses).
    window: Optional[int] = None
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16          # compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32     # master params
    remat: bool = False                # jax.checkpoint each block
    # MoE (0 experts = no MoE):
    n_experts: int = 0
    top_k: int = 2
    # Shared experts (DeepSeek-style): this many always-on expert FFNs
    # beside the routed ones — every token takes routed(top-k) + shared.
    # Stored as ONE fused FFN of width n_shared_experts * d_ff (identical
    # math to summing separate experts, one matmul).
    n_shared_experts: int = 0
    # "dense": exact top-k, every expert computes everything (masked) —
    # simple, shardable over ep as pure weight sharding.
    # "switch": top-1 routing with capacity + real all_to_all token dispatch
    # over the ep axis (parallel/moe.py) — the scalable path.
    moe_impl: str = "dense"
    capacity_factor: float = 1.25
    # Switch-transformer aux weighting: load-balance at 1e-2, z-loss at 1e-3.
    router_aux_weight: float = 0.01
    router_z_weight: float = 1e-3
    # Pipeline schedule: "gpipe", or "circular" with v>1 virtual stages per
    # device (bubble shrinks ~v-fold; needs n_layers % (pp*v) == 0).
    pp_schedule: str = "gpipe"
    pp_virtual_stages: int = 1
    # Sequence parallelism over sp: "ring" (O(T/sp) memory, no head
    # constraint) or "ulysses" (two all_to_alls, full-T flash locally;
    # needs n_heads % sp == 0).  See parallel/ulysses.py for the trade.
    sp_impl: str = "ring"
    # Fused head+cross-entropy: never materializes the [B·T, V] logits
    # through fwd+bwd.  None = auto (see _fused_ce_mode): the dense form
    # (ops/layers.fused_linear_cross_entropy) on single-device and
    # data-only meshes, the tp vocab-parallel form
    # (vocab_parallel_cross_entropy) when tp divides the vocab; sp/pp/ep
    # meshes and QTensor (serving) heads use the standard path.  True asks
    # for fusion even where auto declines (the dense form, relying on
    # GSPMD to partition the chunks); False disables fusion everywhere.
    fused_ce: Optional[bool] = None
    ce_chunk: int = 2048
    # LM-head z-loss (PaLM-style logit-drift stabilizer): adds
    # z_loss · mean(logsumexp(logits)²) to the objective.  All four CE
    # paths (unfused, fused-dense, dp-sharded, tp vocab-parallel)
    # implement it identically.
    z_loss: float = 0.0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def __post_init__(self):
        if self.window is not None and self.window < 1:
            raise ValueError(f"window must be >= 1, got {self.window} "
                             f"(use None for full causal attention)")

    @property
    def kv_heads(self) -> int:
        kv = self.n_heads if self.n_kv_heads is None else self.n_kv_heads
        if kv < 1 or self.n_heads % kv:
            raise ValueError(f"n_heads ({self.n_heads}) must be a positive "
                             f"multiple of n_kv_heads ({kv})")
        return kv


def init_params(cfg: TransformerConfig, rng) -> Dict[str, Any]:
    if cfg.n_shared_experts and not cfg.n_experts:
        raise ValueError(
            "n_shared_experts requires n_experts > 0 — without routed "
            "experts there is nothing to share beside; widen d_ff instead")
    d, f, l = cfg.d_model, cfg.d_ff, cfg.n_layers
    hd = cfg.n_heads * cfg.head_dim
    kvd = cfg.kv_heads * cfg.head_dim
    keys = iter(jax.random.split(rng, 16))

    def norm(shape, scale):
        return (jax.random.normal(next(keys), shape, cfg.param_dtype)
                * scale).astype(cfg.param_dtype)

    layers = {
        "attn_norm": jnp.ones((l, d), cfg.param_dtype),
        "wq": norm((l, d, hd), 1 / math.sqrt(d)),
        "wk": norm((l, d, kvd), 1 / math.sqrt(d)),
        "wv": norm((l, d, kvd), 1 / math.sqrt(d)),
        "wo": norm((l, hd, d), 1 / math.sqrt(hd) / math.sqrt(2 * l)),
        "mlp_norm": jnp.ones((l, d), cfg.param_dtype),
    }
    if cfg.n_experts:
        e = cfg.n_experts
        layers.update(
            router=norm((l, d, e), 1 / math.sqrt(d)),
            e_gate=norm((l, e, d, f), 1 / math.sqrt(d)),
            e_up=norm((l, e, d, f), 1 / math.sqrt(d)),
            e_down=norm((l, e, f, d), 1 / math.sqrt(f) / math.sqrt(2 * l)),
        )
        if cfg.n_shared_experts:
            sf = cfg.n_shared_experts * f
            layers.update(
                s_gate=norm((l, d, sf), 1 / math.sqrt(d)),
                s_up=norm((l, d, sf), 1 / math.sqrt(d)),
                s_down=norm((l, sf, d),
                            1 / math.sqrt(sf) / math.sqrt(2 * l)),
            )
    else:
        layers.update(
            w_gate=norm((l, d, f), 1 / math.sqrt(d)),
            w_up=norm((l, d, f), 1 / math.sqrt(d)),
            w_down=norm((l, f, d), 1 / math.sqrt(f) / math.sqrt(2 * l)),
        )
    return {
        "embed": norm((cfg.vocab_size, d), 1.0),
        "layers": layers,
        "norm_f": jnp.ones((d,), cfg.param_dtype),
        "head": norm((d, cfg.vocab_size), 1 / math.sqrt(d)),
    }


#: weight leaves worth quantizing — the big matmul operands.  Norms are
#: tiny and precision-critical; the router is tiny and decides routing.
_QUANT_KEYS = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
     "e_gate", "e_up", "e_down", "s_gate", "s_up", "s_down"})


def _quantizable(cfg: TransformerConfig, key: str) -> bool:
    """Which layer leaves quantize_params converts.  Switch-MoE expert
    weights stay fp: the capacity-dispatch path (parallel/moe.py) consumes
    raw arrays inside shard_map bodies, and re-plumbing QTensors through
    its all_to_all hops buys little — switch decode is dominated by the
    dense trunk it shares with everything else."""
    if cfg.moe_impl == "switch" and key.startswith("e_"):
        return False
    return key in _QUANT_KEYS


def quantize_params(cfg: TransformerConfig, params) -> Dict[str, Any]:
    """Weight-only int8 quantization (per-row absmax, ``ops/quant.py``).

    Returns a params tree where the embedding table, unembedding head, and
    every per-layer projection/FFN/expert weight are :class:`QTensor`s;
    norms and the router stay fp32.  The tree drops into ``forward``,
    ``decode_step`` and ``generate`` unchanged — weights dequantize at the
    consuming matmul, so HBM streams int8.  That is the serving win:
    steady-state decode at t=1 is weight-bandwidth-bound, and int8 halves
    the bytes per step vs bf16 (~4x vs these fp32 master params).
    """
    layers = {k: (quantize_tensor(v) if _quantizable(cfg, k) else v)
              for k, v in params["layers"].items()}
    return {
        "embed": quantize_tensor(params["embed"]),
        "layers": layers,
        "norm_f": params["norm_f"],
        "head": quantize_tensor(params["head"]),
    }


def _qswiglu(h, w_gate, w_up, w_down, dtype):
    """swiglu unrolled over :func:`_qmm` so int8 weights ride the
    activation-folded form at every matmul — one helper for the dense
    MLP and the MoE shared expert (a fix to the fold must hit both)."""
    g = jax.nn.silu(_qmm(h, w_gate, dtype))
    return _qmm(g * _qmm(h, w_up, dtype), w_down, dtype)


def _mlp(cfg: TransformerConfig, lp, h):
    return _qswiglu(h, lp["w_gate"], lp["w_up"], lp["w_down"], cfg.dtype)


def _zero_aux():
    z = jnp.zeros((), jnp.float32)
    return {"load_balance_loss": z, "z_loss": z, "overflow_frac": z}


def _moe_switch(cfg: TransformerConfig, mesh, lp, h):
    """Expert-parallel switch MoE: flatten tokens and run the all_to_all
    dispatch path (top-k, capacity-limited — not identical math to the
    dense path; choose per config).  Meshless calls use the single-device
    reference with the SAME routing semantics, so a model trained with
    moe_impl="switch" evaluates identically without a mesh.  Returns
    (out, aux) — the router-health metrics loss_fn folds into training."""
    from tfmesos_tpu.parallel.moe import switch_moe, switch_moe_reference
    b, t, d = h.shape
    flat = h.reshape(b * t, d)
    router = lp["router"].astype(cfg.dtype)
    if mesh is None:
        out, aux = switch_moe_reference(flat, router, lp["e_gate"],
                                        lp["e_up"], lp["e_down"],
                                        capacity_factor=cfg.capacity_factor,
                                        top_k=cfg.top_k, return_aux=True)
    else:
        out, aux = switch_moe(flat, router, lp["e_gate"], lp["e_up"],
                              lp["e_down"], mesh,
                              capacity_factor=cfg.capacity_factor,
                              top_k=cfg.top_k, return_aux=True)
    return out.reshape(b, t, d), aux


def _moe(cfg: TransformerConfig, lp, h, ep_axis: Optional[str] = None,
         tp_axis: Optional[str] = None, inbody_ad: bool = False):
    """Top-k routed MoE, computed densely over the expert axis.

    Every expert processes every token and the router mask zeroes the
    unrouted ones — mathematically exact top-k routing whose weights shard
    cleanly over ``ep``.  (A dispatch/all_to_all data path that skips the
    masked compute is the standard optimization; this dense form trades
    FLOPs for simplicity and zero token overflow.)  Returns (out, aux).

    ``ep_axis`` enables the manual-collective form for pipeline stages:
    expert weights arrive as local ``ep`` shards, the (replicated) router
    picks over all E experts, each device computes only its local experts'
    slice of the masked einsum and the partials ``psum`` over ``ep`` —
    bitwise the same math as the GSPMD path.  ``tp_axis`` additionally
    shards every expert's FFN width (Megatron-per-expert: e_gate/e_up
    column-sharded [e_loc, d, f/tp], e_down row-sharded [e_loc, f/tp, d]);
    the e_down contraction then yields a partial sum and the same psum
    covers both axes.

    ``inbody_ad=True`` (the 1F1B train step, which runs ``jax.vjp``
    INSIDE the stage's shard_map) swaps the collectives for the Megatron
    f/g pair: the per-shard-divergent compute (expert einsums and the
    sliced mask) sits between a ``broadcast_replicated_grad`` fan-in and
    a ``psum_replicated_grad`` reduction, so the transposes sum partial
    cotangents exactly once; the router logits and aux losses stay in
    the replicated domain OUTSIDE the fan, where every shard computes
    identical values and identical gradients."""
    e = cfg.n_experts
    logits = (h @ lp["router"].astype(cfg.dtype)).astype(jnp.float32)  # [B,T,E]
    top_vals, top_idx = jax.lax.top_k(logits, cfg.top_k)
    gates = jax.nn.softmax(top_vals, axis=-1)  # [B,T,k]
    # mask[b,t,e] = gate weight if e is among the top-k for (b,t), else 0
    onehot = jax.nn.one_hot(top_idx, e, dtype=jnp.float32)
    mask = (onehot * gates[..., None]).sum(axis=-2)
    psum_axes = tuple(a for a in (ep_axis, tp_axis) if a is not None)
    if inbody_ad and psum_axes:
        from tfmesos_tpu.parallel.collectives import (
            broadcast_replicated_grad, psum_replicated_grad)
        fan = lambda v: broadcast_replicated_grad(v, psum_axes)
        red = lambda v: psum_replicated_grad(v, psum_axes)
    else:
        fan = lambda v: v
        red = ((lambda v: jax.lax.psum(v, psum_axes)) if psum_axes
               else (lambda v: v))
    h_l = fan(h)
    mask = fan(mask)
    if ep_axis is not None:
        eg = lp["e_gate"]
        e_loc = (eg.values if isinstance(eg, QTensor) else eg).shape[0]
        idx = jax.lax.axis_index(ep_axis)
        mask = jax.lax.dynamic_slice_in_dim(mask, idx * e_loc, e_loc, axis=-1)
    g = jax.nn.silu(jnp.einsum("btd,edf->btef", h_l,
                               _wt(lp["e_gate"], cfg.dtype)))
    u = jnp.einsum("btd,edf->btef", h_l, _wt(lp["e_up"], cfg.dtype))
    y = jnp.einsum("btef,efd->bted", g * u, _wt(lp["e_down"], cfg.dtype))
    out = red(jnp.einsum("bted,bte->btd", y, mask.astype(cfg.dtype)))
    probs = jax.nn.softmax(logits, axis=-1)
    f = jnp.sum(onehot, axis=(0, 1, 2)) / (onehot.shape[0] * onehot.shape[1]
                                           * cfg.top_k)
    aux = {
        "load_balance_loss": e * jnp.sum(
            f * jnp.mean(probs, axis=(0, 1))),
        "z_loss": jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
        "overflow_frac": jnp.zeros((), jnp.float32),  # dense path drops none
    }
    return out, aux


def _ffn(cfg: TransformerConfig, mesh, lp, h, ep_axis: Optional[str] = None,
         tp_axis: Optional[str] = None, inbody_ad: bool = False):
    """The block's feed-forward dispatch (dense / switch / dense-MoE) —
    shared by the train and decode paths so they cannot drift.

    ``ep_axis``/``tp_axis`` select the manual-collective MoE forms for use
    inside a pipeline stage's shard_map body (tokens replicated over
    ep/tp, expert weights ep-sharded and/or width-sharded over tp,
    outputs psum'd).  ``inbody_ad=True`` (1F1B) swaps the collectives for
    the transpose-carrying f/g pair — dense top-k MoE only (the switch
    dispatch path still assumes outer differentiation)."""
    if not cfg.n_experts:
        return _mlp(cfg, lp, h), _zero_aux()
    if ep_axis is not None or tp_axis is not None:
        if cfg.moe_impl == "switch":
            if inbody_ad:
                raise ValueError(
                    "moe_impl='switch' does not support in-body AD (1F1B);"
                    " use the dense top-k MoE or pp_schedule="
                    "'gpipe'/'circular'")
            from tfmesos_tpu.parallel.moe import switch_moe_replicated_local
            b, t, d = h.shape
            out, aux = switch_moe_replicated_local(
                h.reshape(b * t, d), lp["router"].astype(cfg.dtype),
                lp["e_gate"], lp["e_up"], lp["e_down"], ep_axis=ep_axis,
                capacity_factor=cfg.capacity_factor, top_k=cfg.top_k,
                tp_axis=tp_axis)
            out = out.reshape(b, t, d)
        else:
            out, aux = _moe(cfg, lp, h, ep_axis=ep_axis, tp_axis=tp_axis,
                            inbody_ad=inbody_ad)
    elif cfg.moe_impl == "switch":
        # Same model function with or without a mesh (switch_moe falls back
        # to its single-device reference when the ep axis is absent).
        out, aux = _moe_switch(cfg, mesh, lp, h)
    else:
        out, aux = _moe(cfg, lp, h)
    if cfg.n_shared_experts:
        # Always-on shared expert(s): dense FFN added to the routed output.
        # The shared weights replicate over ep; under manual tp their width
        # shards like the dense MLP's, so the partial needs its own psum
        # (the f/g pair under in-body AD, fanning h over tp alone — the
        # shared compute is replicated over ep).
        h_s = h
        if inbody_ad and tp_axis is not None:
            from tfmesos_tpu.parallel.collectives import (
                broadcast_replicated_grad, psum_replicated_grad)
            h_s = broadcast_replicated_grad(h, tp_axis)
        shared = _qswiglu(h_s, lp["s_gate"], lp["s_up"], lp["s_down"],
                          cfg.dtype)
        if tp_axis is not None:
            shared = (psum_replicated_grad(shared, tp_axis) if inbody_ad
                      else jax.lax.psum(shared, tp_axis))
        out = out + shared
    return out, aux


def _dense_tp_attn_partition() -> Dict[str, P]:
    """Per-leaf NON-leading-dim PartitionSpecs for a manual-tp stage's
    attention half (Megatron column/row splits) — shared by the
    gpipe/circular pp path and the 1F1B train step so the two tables
    cannot drift."""
    return {
        "attn_norm": P(None, None), "mlp_norm": P(None, None),
        "wq": P(None, None, "tp"), "wk": P(None, None, "tp"),
        "wv": P(None, None, "tp"), "wo": P(None, "tp", None),
    }


def _dense_tp_mlp_partition() -> Dict[str, P]:
    return {"w_gate": P(None, None, "tp"), "w_up": P(None, None, "tp"),
            "w_down": P(None, "tp", None)}


def _moe_param_partition(ep_axis: Optional[str],
                         tp_axis: Optional[str]) -> Dict[str, P]:
    """Per-leaf NON-leading-dim specs for the MoE FFN half: whole experts
    over ep, per-expert Megatron FFN widths over tp, router replicated
    (every device routes over all E experts) — shared by the
    gpipe/circular pp route and the 1F1B train step so the tables cannot
    drift."""
    return {
        "router": P(None, None, None),
        "e_gate": P(None, ep_axis, None, tp_axis),
        "e_up": P(None, ep_axis, None, tp_axis),
        "e_down": P(None, ep_axis, tp_axis, None),
    }


def _shared_expert_partition(tp_axis: Optional[str]) -> Dict[str, P]:
    """Shared (always-on) experts: width-sharded over tp like the dense
    MLP, replicated over ep — shared by both pp routes."""
    return {"s_gate": P(None, None, tp_axis), "s_up": P(None, None, tp_axis),
            "s_down": P(None, tp_axis, None)}


def _replicated_attn_partition() -> Dict[str, P]:
    """Attention half fully replicated (the ep-only stage layout: only
    expert weights shard) — shared by both pp routes."""
    return {
        "attn_norm": P(None, None), "mlp_norm": P(None, None),
        "wq": P(None, None, None), "wk": P(None, None, None),
        "wv": P(None, None, None), "wo": P(None, None, None),
    }


def _block_manual_tp(cfg: TransformerConfig, x, lp, positions,
                     tp_axis: str = "tp", ep_axis: Optional[str] = None,
                     inbody_ad: bool = False,
                     sp_axis: Optional[str] = None):
    """Megatron-style block with MANUAL tp collectives, for use inside a
    pipeline stage (nested shard_map is not allowed there, explicit psum
    is).  ``lp`` leaves arrive as local tp shards: wq/wk/wv column-sharded
    [d, hd/tp] (wk/wv at kv width for GQA — requires tp | kv_heads so the
    local h//g head grouping stays aligned), wo row-sharded [hd/tp, d],
    w_gate/w_up [d, f/tp], w_down [f/tp, d]; norms replicated.  One psum
    after each row-parallel matmul — the textbook 2-collectives-per-block
    tp pattern.  With experts, the FFN half runs the manual-collective MoE
    (``_ffn`` with tp/ep axes: expert widths tp-sharded, experts
    ep-sharded).  Returns (x, aux).

    ``inbody_ad=True`` (dense configs; the 1F1B train step) swaps the
    collectives for the Megatron f/g pair that carry their own
    transposes — required when the stage is differentiated with
    ``jax.vjp`` INSIDE the shard_map, where plain psum's transpose
    double-counts over tp (parallel/collectives.py)."""
    tp = axis_size(tp_axis)
    heads_loc = cfg.n_heads // tp
    kv_loc = cfg.kv_heads // tp
    b, t, _ = x.shape
    if inbody_ad:
        from tfmesos_tpu.parallel.collectives import (
            broadcast_replicated_grad, psum_replicated_grad)
        fan = lambda v_: broadcast_replicated_grad(v_, tp_axis)
        red = lambda v_: psum_replicated_grad(v_, tp_axis)
    else:
        fan = lambda v_: v_
        red = lambda v_: jax.lax.psum(v_, tp_axis)
    h = fan(rms_norm(x, lp["attn_norm"].astype(cfg.dtype)))
    q = _qmm(h, lp["wq"], cfg.dtype).reshape(b, t, heads_loc, cfg.head_dim)
    k = _qmm(h, lp["wk"], cfg.dtype).reshape(b, t, kv_loc, cfg.head_dim)
    v = _qmm(h, lp["wv"], cfg.dtype).reshape(b, t, kv_loc, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    if sp_axis is not None:
        # tp x sp: local HEADS x local SEQUENCE, positions global (the
        # caller offsets them) — see _sp_attend.
        o = _sp_attend(cfg, q, k, v, sp_axis, inbody_ad)
    else:
        o = attend(q, k, v, mesh=None, causal=True,
                   window=cfg.window)  # local heads
    x = x + red(_qmm(o.reshape(b, t, -1), lp["wo"], cfg.dtype))
    h = rms_norm(x, lp["mlp_norm"].astype(cfg.dtype))
    if cfg.n_experts:
        # The MoE half fans/reduces internally (over ep AND tp — the f/g
        # pair when inbody_ad, plain psum otherwise).
        ffn, aux = _ffn(cfg, None, lp, h, ep_axis=ep_axis, tp_axis=tp_axis,
                        inbody_ad=inbody_ad)
        return x + ffn, aux
    ffn = _mlp(cfg, lp, fan(h))                   # local d_ff shard
    return x + red(ffn), _zero_aux()


def _sp_gather_attention(cfg: TransformerConfig, q, k, v, axis: str):
    """Sequence-parallel attention by K/V all_gather: the local q shard
    attends the FULL gathered sequence with global-position masks.

    This is the sp form for stage bodies that run inside DIVERGENT
    control flow (the 1F1B tick's ``lax.switch``): an all_gather lowers
    to a SUBGROUP collective over the sp group — like the tp psums the
    fused schedule already runs in branches — whereas the einsum ring's
    ``ppermute`` lowers with a global participant set and deadlocks
    when pipeline stages take different branches.  Trades the ring's
    overlapped O(T/sp) K/V residency for one gather; q/dq stay sharded
    and the all_gather transposes to a reduce_scatter, so in-body vjp
    sums per-shard dK/dV contributions exactly once."""
    scale = 1.0 / math.sqrt(cfg.head_dim)
    tq = q.shape[1]
    # Gather the NARROW (kv-width) K/V and broadcast GQA groups locally
    # afterwards: 1/g the collective bytes and gathered residency.
    kg = jax.lax.all_gather(k, axis, axis=1, tiled=True)    # [B, T, KV, D]
    vg = jax.lax.all_gather(v, axis, axis=1, tiled=True)
    g = q.shape[2] // kg.shape[2]
    if g > 1:
        kg = jnp.repeat(kg, g, axis=2)
        vg = jnp.repeat(vg, g, axis=2)
    tk = kg.shape[1]
    idx = jax.lax.axis_index(axis)
    qpos = idx * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32) * scale,
                   kg.astype(jnp.float32))
    bad = kpos > qpos
    if cfg.window is not None:
        bad = bad | (kpos < qpos - (cfg.window - 1))
    s = jnp.where(bad[None, None], float("-inf"), s)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vg.astype(jnp.float32))
    return o.astype(q.dtype)


def _sp_attend(cfg: TransformerConfig, q, k, v, sp_axis: str,
               inbody_ad: bool):
    """Manual sequence-parallel attention dispatch, shared by the dense
    and manual-tp stage blocks (q/k/v may be tp-local head shards): the
    K/V-gather form under in-body AD (1F1B's divergent branches; GQA
    broadcasts AFTER the gather), the einsum ring under outer AD
    (lockstep gpipe ticks; the ring helper matches heads one-for-one,
    so GQA broadcasts before the hops)."""
    if inbody_ad:
        return _sp_gather_attention(cfg, q, k, v, sp_axis)
    g = q.shape[2] // k.shape[2]
    if g > 1:
        k = jnp.repeat(k, g, axis=2)
        v = jnp.repeat(v, g, axis=2)
    from tfmesos_tpu.parallel.ring_attention import ring_attention_local
    return ring_attention_local(q, k, v, axis=sp_axis, causal=True,
                                window=cfg.window)


def _block(cfg: TransformerConfig, mesh: Optional[Mesh], x, lp, positions,
           ep_axis: Optional[str] = None, inbody_ad: bool = False,
           sp_axis: Optional[str] = None):
    """One transformer block.  ``sp_axis`` selects the MANUAL
    sequence-parallel form for use inside a pipeline stage's shard_map
    body (a nested shard_map is not allowed there): activations arrive
    as local sequence shards and ``positions`` must already be GLOBAL.
    Attention runs the einsum ring (``ring_attention_local``) under
    outer AD, or the K/V-gather form under ``inbody_ad`` (the 1F1B
    tick's branches — see ``_sp_gather_attention``)."""
    b, t, d = x.shape
    h = rms_norm(x, lp["attn_norm"].astype(cfg.dtype))
    q = _qmm(h, lp["wq"], cfg.dtype).reshape(b, t, cfg.n_heads, cfg.head_dim)
    k = _qmm(h, lp["wk"], cfg.dtype).reshape(b, t, cfg.kv_heads, cfg.head_dim)
    v = _qmm(h, lp["wv"], cfg.dtype).reshape(b, t, cfg.kv_heads, cfg.head_dim)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    if sp_axis is not None:
        o = _sp_attend(cfg, q, k, v, sp_axis, inbody_ad)
    else:
        # GQA (kv_heads < n_heads) flows through attend() at kv width:
        # the flash kernels map q head h -> kv head h // (H/KV) in their
        # index maps, so training never materializes the repeated K/V;
        # the sp impls broadcast up internally.
        o = attend(q, k, v, mesh=mesh, causal=True, sp_impl=cfg.sp_impl,
                   window=cfg.window)
    x = x + _qmm(o.reshape(b, t, -1), lp["wo"], cfg.dtype)
    h = rms_norm(x, lp["mlp_norm"].astype(cfg.dtype))
    ffn, aux = _ffn(cfg, mesh, lp, h, ep_axis=ep_axis, inbody_ad=inbody_ad)
    return x + ffn, aux


def forward(cfg: TransformerConfig, params, tokens, mesh: Optional[Mesh] = None,
            return_aux: bool = False):
    """tokens [B, T] int32 → logits [B, T, V] (plus per-layer-averaged router
    aux metrics when ``return_aux``)."""
    x, aux = forward_hidden(cfg, params, tokens, mesh)
    logits = _qmm(x, params["head"], cfg.dtype)
    return (logits, aux) if return_aux else logits


def forward_hidden(cfg: TransformerConfig, params, tokens,
                   mesh: Optional[Mesh] = None):
    """The trunk: tokens [B, T] → (final-norm hidden states [B, T, d],
    per-layer-averaged router aux metrics).  ``forward`` applies the
    unembedding head on top; ``loss_fn`` may instead feed the hidden states
    to the fused head+cross-entropy, which never materializes full logits.

    Sequence positions are global even when activations are sp-sharded:
    ring attention receives the full logical sequence sharded along T, and
    rope positions follow the global index.
    """
    b, t = tokens.shape
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))

    block = lambda x_, lp_, pos: _block(cfg, mesh, x_, lp_, pos)
    if cfg.remat:
        block = jax.checkpoint(block)

    aux = _zero_aux()
    pp = mesh.shape.get("pp", 1) if mesh is not None else 1
    if pp > 1:
        from tfmesos_tpu.parallel.pipeline import pipeline_apply
        tp = mesh.shape.get("tp", 1)
        n_chunks = pp * cfg.pp_virtual_stages
        if cfg.n_layers % n_chunks:
            raise ValueError(f"{cfg.n_layers} layers not divisible into "
                             f"{n_chunks} pipeline chunks")
        per = cfg.n_layers // n_chunks
        stacked = jax.tree_util.tree_map(
            lambda p: p.reshape(n_chunks, per, *p.shape[1:]),
            params["layers"])

        # Stages compose with tp via MANUAL collectives (weights sharded
        # over tp, one psum per row-parallel matmul) — nested shard_map is
        # not allowed inside the pipeline's own shard_map.
        ep = mesh.shape.get("ep", 1)
        ep_axis = "ep" if (cfg.n_experts and ep > 1) else None
        # pp x sp: shard the SEQUENCE over sp inside stages — manual
        # ring/gather attention with global rope positions (dense tp
        # stages compose: local heads x local sequence).  The sequence
        # stays replicated when it does not divide over sp and for
        # switch MoE (its capacity-based token dropping is a
        # FULL-sequence competition — deciding it per T/sp shard would
        # silently change which tokens drop).
        sp = mesh.shape.get("sp", 1)
        sp_axis = ("sp" if (sp > 1 and t % sp == 0
                            and not (cfg.n_experts
                                     and cfg.moe_impl == "switch"))
                   else None)
        if tp > 1:
            if cfg.kv_heads % tp:
                raise ValueError(
                    f"pp x tp needs tp ({tp}) to divide kv_heads "
                    f"({cfg.kv_heads}) so the local head grouping stays "
                    f"aligned; lower tp or raise kv_heads")
            stage_block = lambda c, lp_, pos: _block_manual_tp(
                cfg, c, lp_, pos, ep_axis=ep_axis, sp_axis=sp_axis)
            partition = _dense_tp_attn_partition()
            if cfg.n_experts:
                # Per-expert Megatron: FFN widths shard over tp, whole
                # experts over ep (when present).
                partition.update(_moe_param_partition(ep_axis, "tp"))
                if cfg.n_shared_experts:
                    partition.update(_shared_expert_partition("tp"))
            else:
                partition.update(_dense_tp_mlp_partition())
        else:
            stage_block = lambda c, lp_, pos: _block(cfg, None, c, lp_, pos,
                                                     ep_axis=ep_axis,
                                                     sp_axis=sp_axis)
            # Expert weights shard over ep inside the stage (the router
            # stays replicated so every device routes over all E experts).
            partition = None
            if ep_axis:
                partition = {
                    **_replicated_attn_partition(),
                    **_moe_param_partition(ep_axis, None),
                }
                if cfg.n_shared_experts:
                    partition.update(_shared_expert_partition(None))
        if cfg.remat:
            stage_block = jax.checkpoint(stage_block)

        # Router aux rides the pipeline when experts are on: stages return
        # per-chunk aux means and pipeline_apply averages them over chunks
        # x microbatches (the grad-accumulation estimator of the non-pp
        # batch statistics).
        with_aux = _zero_aux() if cfg.n_experts else False

        def stage_fn(stage_params, h):
            pos = jnp.arange(h.shape[1], dtype=jnp.int32)
            if sp_axis is not None:
                # Local shard i holds global positions
                # [i*t_loc, (i+1)*t_loc): rope and the ring's causal
                # bounds both follow the global index.
                pos = pos + jax.lax.axis_index(sp_axis) * h.shape[1]
            pos = jnp.broadcast_to(pos, h.shape[:2])

            def body(carry, lp):
                out, layer_aux = stage_block(carry, lp, pos)
                return out, layer_aux
            out, stacked_aux = jax.lax.scan(body, h, stage_params)
            if with_aux is False:
                return out
            return out, jax.tree_util.tree_map(jnp.mean, stacked_aux)

        x = pipeline_apply(stage_fn, stacked, x, mesh,
                           param_partition=partition,
                           schedule=cfg.pp_schedule,
                           virtual_stages=cfg.pp_virtual_stages,
                           with_aux=with_aux, seq_axis=sp_axis)
        if with_aux is not False:
            x, aux = x
    else:
        def body(carry, lp):
            out, layer_aux = block(carry, lp, positions)
            return out, layer_aux
        x, stacked_aux = jax.lax.scan(body, x, params["layers"])
        aux = jax.tree_util.tree_map(jnp.mean, stacked_aux)

    return rms_norm(x, params["norm_f"].astype(cfg.dtype)), aux


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               dtype=None, quantized: bool = False) -> Dict[str, Any]:
    """KV cache for autoregressive decoding: stacked
    [L, B, KV, M, head_dim] K/V buffers — kv-head-major with (seq,
    head_dim) trailing, the flash-decode kernel's native tiling, so
    decode never transposes cache-sized data.  The layer scan CARRIES
    the stacked buffers and each step writes its token slot in place at
    its layer index (``_cache_write``); per-step HBM traffic is the slot
    write plus what attention actually reads — never a restack of the
    whole buffer.

    ``quantized=True`` stores the cache as int8 :class:`QTensor`s with
    one fp32 absmax scale per (layer, batch, head, position), held
    LANE-MAJOR ([L, B, KV, 1, M] — positions on the trailing dim, as the
    kernel consumes them) — long-context decode streams the whole cache
    every step, so halving its bytes vs bf16 is the long-prompt analogue
    of weight-only int8.  Writes quantize the incoming K/V chunk; reads
    fold the scales in-kernel (or dequantize at the attention einsum).

    With sliding-window attention (``cfg.window``) the buffer is a ROLLING
    cache of ``window`` slots (slot = position mod window): a position's
    slot is reclaimed exactly when it leaves the window, so memory and
    per-step cache bandwidth are O(window) regardless of how long
    generation runs.
    """
    if cfg.window is not None:
        max_len = min(max_len, cfg.window)
    shape = (cfg.n_layers, batch, cfg.kv_heads, max_len, cfg.head_dim)
    if quantized:
        if dtype is not None:
            raise ValueError("init_cache: dtype and quantized=True conflict "
                             "(an int8 cache's dtypes are fixed)")

        def buf():
            # Distinct buffers for k and v, matching the fp path — aliasing
            # one QTensor for both halves would break if decode ever donates
            # the cache (the same buffer donated twice).
            return QTensor(jnp.zeros(shape, jnp.int8),
                           jnp.ones(shape[:-2] + (1, max_len), jnp.float32))

        return {"k": buf(), "v": buf()}
    dtype = dtype or cfg.dtype
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def init_paged_cache(cfg: TransformerConfig, n_pages: int,
                     page_size: int = 128, dtype=None,
                     quantized: bool = False) -> Dict[str, Any]:
    """A PAGED KV cache: one physical pool of ``n_pages`` pages per layer,
    shared by every sequence — rows map logical cache blocks to pool
    pages through a ``page_table`` ([B, NP] int32, built by
    :class:`PageAllocator`), so mixed-length sequences consume memory
    proportional to their LENGTH, not to a per-row max_len buffer (the
    PagedAttention serving layout; docs/SERVING.md).

    Pass ``{"k", "v", "pages"}`` (this dict plus the allocator's table
    under ``"pages"``) to ``decode_step``.  ``quantized=True`` stores the
    pool as int8 with per-position scales (the paged kernel folds them
    into the score rows, so HBM streams int8 pages).  Windowed (rolling)
    configs address by slot and don't page.
    """
    if cfg.window is not None:
        raise ValueError("paged caches do not compose with sliding-window "
                         "configs (rolling caches address by slot)")
    if page_size % 8 or page_size > 1024:
        raise ValueError(f"page_size ({page_size}) must be a multiple of "
                         f"8 and <= 1024 (the kernel's tile shape)")
    if quantized:
        if dtype is not None:
            raise ValueError("init_paged_cache: dtype and quantized=True "
                             "conflict (an int8 pool's dtypes are fixed)")
        shape = (cfg.n_layers, n_pages, cfg.kv_heads, page_size,
                 cfg.head_dim)

        def buf():
            # Scales are LANE-MAJOR ([..., 1, page] — positions on the
            # trailing dim), deviating from QTensor's usual trailing-1
            # convention, so the kernel consumes them without a per-call
            # transpose of pool-capacity-sized data.  flash_decode_paged
            # and its reference are the only consumers.
            return QTensor(jnp.zeros(shape, jnp.int8),
                           jnp.ones(shape[:-2] + (1, page_size),
                                    jnp.float32))

        return {"k": buf(), "v": buf()}
    dtype = dtype or cfg.dtype
    # (page, head_dim) trailing — the kernel's native layout, so serving
    # never transposes the shared pool.
    shape = (cfg.n_layers, n_pages, cfg.kv_heads, page_size, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


class PageAllocator:
    """Host-side page bookkeeping for :func:`init_paged_cache` (numpy,
    no jax): a free list over ``n_pages`` and per-row page lists.  The
    serving loop allocates pages as sequences grow (``ensure``), frees
    them when requests finish (``release``), and hands ``table()`` to
    ``decode_step`` each call.  Rows it serves may come and go — that
    admission control is the caller's loop, as docs/SERVING.md notes."""

    def __init__(self, n_pages: int, page_size: int):
        import numpy as np

        self._np = np
        self.page_size = int(page_size)
        self.free = list(range(n_pages - 1, -1, -1))
        self.rows: Dict[int, list] = {}
        # Optional allocation-pressure hook: called with the free list
        # empty, returns True after putting at least one page back on it
        # (the serving prefix cache reclaims zero-ref cached pages this
        # way — retained pages stay resident until someone actually
        # needs the HBM, never blocking an allocation that could be
        # served by evicting).
        self.reclaim = None

    def _take(self) -> int:
        if not self.free:
            while self.reclaim is not None and self.reclaim():
                if self.free:
                    break
            if not self.free:
                raise RuntimeError("page pool exhausted")
        return self.free.pop()

    def ensure(self, row: int, length: int) -> None:
        """Back positions [0, length) of ``row`` with pages."""
        need = -(-int(length) // self.page_size)
        pages = self.rows.setdefault(row, [])
        while len(pages) < need:
            pages.append(self._take())

    def release(self, row: int) -> None:
        self.free.extend(reversed(self.rows.pop(row, [])))

    def reserve_page(self) -> int:
        """Permanently take one page out of circulation and return its id
        (serving uses this as a write sink for inactive decode rows)."""
        return self._take()

    def free_count(self) -> int:
        return len(self.free)

    def allocated(self, row: int) -> int:
        """Pages currently backing ``row``."""
        return len(self.rows.get(row, []))

    def table(self, rows, width: Optional[int] = None,
              fill: int = 0) -> "jnp.ndarray":
        """[len(rows), NP] table.  NP defaults to the longest listed row's
        page count; pass ``width`` to fix the shape (one compiled decode
        shape for a whole serving run).  Unused entries hold ``fill`` —
        never FETCHED (the per-row block bound stops first), but batched
        decode steps WRITE one position per row each step, so continuous
        serving points them at a reserved sink page."""
        np = self._np
        lists = [self.rows.get(r, []) for r in rows]
        if width is None:
            width = max(1, max((len(p) for p in lists), default=1))
        t = np.full((len(lists), width), fill, np.int32)
        for i, pages in enumerate(lists):
            t[i, :len(pages)] = pages
        return jnp.asarray(t)


def _paged_cache_write(pool, chunk, li, page_table, pos):
    """Write a [B, t, H, Dh] chunk into layer ``li`` of the STACKED page
    pool ([L, P, KV, page, Dh]; int8 QTensors quantize per position on
    the way in) at logical positions ``pos..pos+t-1`` per row (``pos``
    scalar or [B]): one scatter over (page, offset) pairs chased through
    the table.  The pool is a layer-scan CARRY, so the scatter updates
    it in place — per-step traffic is the written slots, never the
    pool."""
    b, t = chunk.shape[:2]
    ps = (pool.values if isinstance(pool, QTensor) else pool).shape[3]
    posv = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    lpos = posv[:, None] + jnp.arange(t, dtype=jnp.int32)[None]   # [B, t]
    # Clamp the block index explicitly: serving parks inactive rows at
    # position max_len, whose block can be one past the table width when
    # max_len is a page multiple.  A parked row's whole table row is the
    # sink page, so the clamped entry is still the sink — but make that a
    # guarantee of this code, not of out-of-bounds gather semantics.
    blk = jnp.minimum(lpos // ps, page_table.shape[1] - 1)
    pages = jnp.take_along_axis(page_table, blk, axis=1).reshape(-1)
    offs = (lpos % ps).reshape(-1)

    def put(buf, x):
        return buf.at[li, pages, :, offs].set(
            x.reshape(b * t, *x.shape[2:]).astype(buf.dtype))

    if isinstance(pool, QTensor):
        from tfmesos_tpu.ops.quant import quantize_int8_reference
        vals, scale = quantize_int8_reference(chunk)
        # Scales pool is lane-major [L, P, KV, 1, page] (see
        # init_paged_cache): scatter at (layer, page, :, 0, offset).
        scales = pool.scales.at[li, pages, :, 0, offs].set(
            scale.reshape(b * t, scale.shape[2]))
        return QTensor(put(pool.values, vals), scales)
    return put(pool, chunk)


def _paged_cache_write_all(pool, chunks, page_table, pos):
    """Commit ALL layers' deferred chunks ([L, B, t, KV, Dh], stacked by
    the decode layer scan) in ONE scatter per pool leaf — 2L scatters
    per step become 2 (one scatter op costs ~0.5 ms on TPU regardless
    of payload, so the op COUNT is the serving decode's write cost).
    t = 1 is the steady-state deferred token; t > 1 the fused
    multi-row step (speculative verify / chunked-prefill tails), whose
    per-token (page, offset) pairs chase the table exactly like the
    per-layer ``_paged_cache_write`` — same index math (sink clamp
    included) and same per-row absmax int8 rule."""
    L, b, t, kvh, dh = chunks.shape
    ps = (pool.values if isinstance(pool, QTensor) else pool).shape[3]
    posv = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    lpos = posv[:, None] + jnp.arange(t, dtype=jnp.int32)[None]   # [B, t]
    blk = jnp.minimum(lpos // ps, page_table.shape[1] - 1)
    pages = jnp.take_along_axis(page_table, blk, axis=1).reshape(-1)
    offs = (lpos % ps).reshape(-1)
    # [L, B, t, KV, Dh] -> [B*t, L, KV, Dh] update rows, (page, offset)
    # indexed per (row, token).
    x = chunks.transpose(1, 2, 0, 3, 4).reshape(b * t, L, kvh, dh)

    def put(buf, x):
        # Advanced indices (pages, offs) around the slices front the
        # row dim: updates arrive [B*t, L, KV, Dh'].
        return buf.at[:, pages, :, offs].set(x.astype(buf.dtype))

    if isinstance(pool, QTensor):
        from tfmesos_tpu.ops.quant import quantize_int8_reference
        vals, scale = quantize_int8_reference(x)
        scales = pool.scales.at[:, pages, :, 0, offs].set(
            scale[..., 0])
        return QTensor(put(pool.values, vals), scales)
    return put(pool, x)


def _cache_write(cache, chunk, li, pos, rolling: bool = False):
    """Insert a [B, t, H, Dh] K or V chunk at position ``pos`` of layer
    ``li`` of the STACKED cache ([L, B, KV, M, Dh]), quantizing on the
    way in when the cache is int8 (the same per-row absmax rule as
    weight quantization — ops/quant.py).  The cache is a layer-scan
    CARRY and every path below is an indexed in-place update on the full
    buffer — one slot's traffic per step, never a buffer restack.

    ``pos`` may be a [B] vector (ragged serving: each row writes at its
    own position — a vmapped per-row dynamic slice; non-rolling caches
    only).

    ``rolling`` (window configs): position p writes slot p mod M — a
    single-token step is one wrapped dynamic slice; a longer chunk
    (prefill, static ``pos``) keeps its last M tokens via a modular
    scatter.  Non-rolling caches keep the plain dynamic-slice write (which
    supports traced multi-token positions — the buffer never wraps).
    """
    m = (cache.values if isinstance(cache, QTensor) else cache).shape[3]
    t = chunk.shape[1]
    ragged = getattr(pos, "ndim", 0) == 1
    if ragged and rolling:
        raise ValueError("ragged positions do not compose with rolling "
                         "(windowed) caches")

    def _put(buf, x, axis):
        """Write ``x`` (shaped like ``buf[li]``, t positions on ``axis``
        of the full buffer) at ``pos`` of layer ``li`` — values and
        scales share every branch; only the position axis differs (3 for
        [L, B, KV, M, Dh'] values, 4 for [L, B, KV, 1, M] scales)."""
        def start(p, rank, ax):
            s = [0] * rank
            s[0], s[ax] = li, p
            return tuple(s)

        if ragged:
            # Per row b: buf[:, b] gets its row's chunk at its own
            # position (the batch dim drops, shifting the axis by one).
            return jax.vmap(
                lambda b_, x_, p_: jax.lax.dynamic_update_slice(
                    b_, x_[None], start(p_, b_.ndim, axis - 1)),
                in_axes=(1, 0, 0), out_axes=1)(buf, x, pos)
        if not rolling:
            return jax.lax.dynamic_update_slice(
                buf, x[None], start(pos, buf.ndim, axis))
        if t == 1:
            return jax.lax.dynamic_update_slice(
                buf, x[None], start(pos % m, buf.ndim, axis))
        if not isinstance(pos, int):
            raise ValueError("multi-token rolling-cache writes need a "
                             "static position (prefill); decode rolls one "
                             "token at a time")
        if pos + t <= m:
            return jax.lax.dynamic_update_slice(
                buf, x[None], start(pos, buf.ndim, axis))
        # Wrapping prefill (one-time): modular scatter on the layer slice,
        # written back whole — chunk-sized work at a static position.
        keep = jax.lax.slice_in_dim(x, max(0, t - m), t, axis=axis - 1)
        idx = (jnp.arange(pos + t - keep.shape[axis - 1], pos + t)) % m
        lay = jax.lax.dynamic_index_in_dim(buf, li, 0, keepdims=False)
        lay = lay.at[(slice(None),) * (axis - 1) + (idx,)].set(keep)
        return jax.lax.dynamic_update_slice(
            buf, lay[None], start(0, buf.ndim, axis))

    def put(buf, x):
        # x [B, t, KV, Dh'] -> head-major [B, KV, t, Dh'] (a chunk-sized
        # transpose; the cache itself is already head-major).
        return _put(buf, x.transpose(0, 2, 1, 3).astype(buf.dtype), 3)

    def put_scales(buf, s):
        # s [B, t, KV, 1] -> lane-major [B, KV, 1, t] (positions on the
        # trailing dim, matching the [L, B, KV, 1, M] scales buffer).
        return _put(buf, s.transpose(0, 2, 3, 1), 4)

    if isinstance(cache, QTensor):
        from tfmesos_tpu.ops.quant import quantize_int8_reference
        vals, scale = quantize_int8_reference(chunk)
        return QTensor(put(cache.values, vals),
                       put_scales(cache.scales, scale))
    return put(cache, chunk)


def _cache_read(cache, li, dtype):
    """The [B, KV, M, Dh] view of layer ``li`` that einsum attention
    consumes; int8 caches dequantize here (the convert+scale fuses into
    the einsum, so HBM streams int8); fp caches pass through at their own
    dtype (a caller-widened fp32 cache keeps fp32 attention math, as
    before).  Kernel paths never call this — they read the stacked
    buffer directly at the layer index."""
    from tfmesos_tpu.ops.attention import _dequant_lane_major

    take = lambda a: jax.lax.dynamic_index_in_dim(a, li, 0, keepdims=False)
    if isinstance(cache, QTensor):
        return _dequant_lane_major(
            QTensor(take(cache.values), take(cache.scales)), dtype)
    return take(cache)


def cache_specs(cfg: TransformerConfig, mesh: Mesh,
                quantized: bool = False) -> Dict[str, Any]:
    """PartitionSpecs for the KV cache ([L, B, KV, M, Dh]): batch over the
    data axes, heads over tp — the decode analogue of ``partition_specs``.
    Place the cache (and params) with these and jit
    ``decode_step(..., sharded=True)``: every op is then a plain einsum,
    so GSPMD inserts the tp collectives — no manual decode variant
    needed.  With GQA the cache's head axis is ``kv_heads``, so tp must
    divide it.  ``quantized=True`` mirrors an int8 ``init_cache``: each
    leaf becomes a QTensor of specs (the lane-major scales
    [L, B, KV, 1, M] shard on the same leading dims)."""
    from tfmesos_tpu.parallel.sharding import data_axes
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and cfg.kv_heads % tp:
        raise ValueError(
            f"cache_specs: tp ({tp}) must divide kv_heads "
            f"({cfg.kv_heads}) to shard the KV cache's head axis")
    spec = _filter_spec(P(None, data_axes(mesh), "tp", None, None), mesh)
    if quantized:
        spec = QTensor(values=spec, scales=spec)
    return {"k": spec, "v": spec}


def paged_cache_specs(cfg: TransformerConfig, mesh: Mesh,
                      quantized: bool = False) -> Dict[str, Any]:
    """PartitionSpecs for a PAGED pool ([L, P, KV, page, Dh]): the PAGE
    axis over the data axes — each data shard owns a sub-pool that its
    rows' page tables index with shard-LOCAL ids (serving's allocator
    maintains that invariant) — and kv heads over tp.  Place the pool
    (and params per ``partition_specs``) with these and jit
    ``decode_step(..., sharded=True, mesh=mesh)``: the page
    gather/scatter then runs per shard inside a shard_map island
    (``_sharded_paged_step``) while everything around it stays plain
    GSPMD einsums.  ``quantized=True`` mirrors an int8
    ``init_paged_cache`` (lane-major scales share the values' spec)."""
    from tfmesos_tpu.parallel.sharding import data_axes
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and (cfg.kv_heads % tp or cfg.n_heads % tp):
        raise ValueError(
            f"paged_cache_specs: tp ({tp}) must divide kv_heads "
            f"({cfg.kv_heads}) and n_heads ({cfg.n_heads}) to shard the "
            f"pool's head axis")
    spec = _filter_spec(P(None, data_axes(mesh), "tp", None, None), mesh)
    if quantized:
        # Lane-major scales [L, P, KV, 1, page]: same sharded dims, and
        # the trailing entries are already None.
        spec = QTensor(values=spec, scales=spec)
    return {"k": spec, "v": spec}


def _check_sharded_paged(cfg: TransformerConfig, mesh: Optional[Mesh],
                         batch: int, n_pages: int):
    """Validate a sharded paged decode call; returns (data_axes_prod, tp)."""
    if mesh is None:
        raise ValueError(
            "sharded paged decode needs the mesh: place the pool per "
            "paged_cache_specs and pass decode_step(..., sharded=True, "
            "mesh=mesh)")
    real = {a for a, s in mesh.shape.items() if s > 1}
    if not real <= {"dp", "fsdp", "tp"}:
        raise ValueError(
            f"sharded paged decode runs on data (dp/fsdp) x tp meshes; "
            f"got axes {sorted(real)}")
    nd = 1
    for a in ("dp", "fsdp"):
        nd *= mesh.shape.get(a, 1)
    tp = mesh.shape.get("tp", 1)
    if cfg.kv_heads % tp or cfg.n_heads % tp:
        raise ValueError(
            f"tp ({tp}) must divide kv_heads ({cfg.kv_heads}) and "
            f"n_heads ({cfg.n_heads})")
    if batch % nd:
        raise ValueError(
            f"batch ({batch}) must divide over the data axes ({nd})")
    if n_pages % nd:
        raise ValueError(
            f"pool pages ({n_pages}) must divide over the data axes "
            f"({nd}) — each shard owns an equal sub-pool")
    return nd, tp


def _sharded_paged_step(cfg: TransformerConfig, mesh: Mesh, q, k, v, ck,
                        cv, li, pages, positions, attend: bool = True):
    """Paged write + paged attention as ONE shard_map island over the
    ``paged_cache_specs`` layout ([L, P, KV, page, Dh] pools, carried
    whole with ``li`` the layer index — writes scatter in place at the
    index and the kernel reads through its scalar prefetch, exactly as
    on the single-host path).  Each data shard owns a sub-pool whose
    pages its rows' table entries index LOCALLY, so the gather/scatter
    indirection never crosses shards; heads shard over tp with GQA
    grouping preserved per shard (tp divides both head counts).  No
    collective runs inside — the tp output reduction stays with GSPMD
    at the surrounding wo matmul.  ``attend=False`` (prefill from an
    empty cache: the chunk attends only to itself) writes the pages and
    lets the caller compute self-attention as a plain partitionable
    einsum."""
    from tfmesos_tpu.parallel.sharding import data_axes

    da = data_axes(mesh)
    qkv = _filter_spec(P(da, None, "tp", None), mesh)
    pool = _filter_spec(P(None, da, "tp", None, None), mesh)
    if isinstance(ck, QTensor):
        pool = QTensor(values=pool, scales=pool)
    tbl = _filter_spec(P(da, None), mesh)
    li = jnp.asarray(li, jnp.int32)

    def write(ck, cv, k, v, li, pages, posv):
        ck = _paged_cache_write(ck, k, li, pages, posv)
        cv = _paged_cache_write(cv, v, li, pages, posv)
        return ck, cv

    if not attend:
        def local(q, k, v, ck, cv, li, pages, positions):
            ck, cv = write(ck, cv, k, v, li, pages, positions[:, 0])
            return ck, cv

        fn = shard_map(local, mesh=mesh,
                       in_specs=(qkv, qkv, qkv, pool, pool, P(), tbl, tbl),
                       out_specs=(pool, pool), check_vma=False)
        ck, cv = fn(q, k, v, ck, cv, li, pages, positions)
        return None, ck, cv

    t = q.shape[1]
    m = _cache_logical_len(ck, pages)
    kernel_kw = _decode_kernel_kwargs(cfg, m, t, False)

    def local(q, k, v, ck, cv, li, pages, positions):
        posv = positions[:, 0]
        ck, cv = write(ck, cv, k, v, li, pages, posv)
        from tfmesos_tpu.ops.attention import (_paged_decode_reference,
                                               flash_decode_paged)
        if kernel_kw is not None:
            o = flash_decode_paged(q, ck, cv, pages, posv, layer=li,
                                   **kernel_kw)
        else:
            o = _paged_decode_reference(q, ck, cv, pages, posv,
                                        1.0 / math.sqrt(cfg.head_dim),
                                        layer=li)
        return o, ck, cv

    fn = shard_map(local, mesh=mesh,
                   in_specs=(qkv, qkv, qkv, pool, pool, P(), tbl, tbl),
                   out_specs=(qkv, pool, pool), check_vma=False)
    return fn(q, k, v, ck, cv, li, pages, positions)


def _decode_kernel_kwargs(cfg: TransformerConfig, m: int, t: int,
                          sharded: bool, mesh: Optional[Mesh] = None,
                          batch: Optional[int] = None):
    """kwargs for ``flash_decode`` when the cache-bounded kernel applies,
    else None — single tokens (t=1) and short chunks (speculative verify
    / chunked prefill; capped so the resident [t·g, block] score rows
    stay kernel-shaped).  TPU only; fp or int8 QTensor caches (the kernel
    folds the int8 scales into the score rows); full buffers
    (rolling-window caches address by slot); m large enough that the
    O(pos) HBM bound beats the kernel's fixed cost.

    Sharded decode: a pallas_call cannot be GSPMD-partitioned, but with
    an explicit ``mesh`` whose axes are data + tp (the ``cache_specs``
    layout) the kernel runs per shard under a shard_map
    (``sharded_flash_decode``); other meshes keep the einsum."""
    if (t > 64 or cfg.window is not None or m < 512
            or jax.default_backend() != "tpu"):
        return None
    if not sharded:
        return {}
    return {} if _shard_map_mesh_ok(cfg, mesh, batch) else None


def _cache_logical_len(cache_leaf, pages=None) -> int:
    """Logical attended length of a stacked cache leaf: slots of a
    [L, B, KV, M, Dh] linear buffer, or table-width x page for a
    [L, P, KV, page, Dh] pool (the position axis is 3 in both layouts —
    ONE place that knows it)."""
    buf = cache_leaf.values if isinstance(cache_leaf, QTensor) else \
        cache_leaf
    return pages.shape[1] * buf.shape[3] if pages is not None \
        else buf.shape[3]


def _shard_map_mesh_ok(cfg: TransformerConfig, mesh: Optional[Mesh],
                       batch: Optional[int],
                       need_n_heads_div: bool = False) -> bool:
    """Whether a per-shard kernel (shard_map over the ``cache_specs`` /
    ``paged_cache_specs`` layout) is eligible on this mesh: real axes
    within data (dp/fsdp) + tp, the batch dividing over the data axes
    (the GSPMD einsum has no such constraint, so indivisible batches
    fall back), and tp dividing kv_heads (plus n_heads when the caller
    shards full-width q heads).  ONE definition of the eligibility rule
    — the decode and prefill kernel gates both call it."""
    if mesh is None:
        return False
    real = {a for a, s in mesh.shape.items() if s > 1}
    tp = mesh.shape.get("tp", 1)
    nd = 1
    for a in ("dp", "fsdp"):
        nd *= mesh.shape.get(a, 1)
    if batch is not None and batch % nd:
        return False
    if need_n_heads_div and cfg.n_heads % tp:
        return False
    return real <= {"dp", "fsdp", "tp"} and cfg.kv_heads % tp == 0


def _prefill_kernel_kwargs(cfg: TransformerConfig, mesh: Optional[Mesh],
                           batch: int, t: int):
    """kwargs for ``sharded_flash_attention`` on the SHARDED prefill path,
    else None (keep the GSPMD ``mha_reference`` einsum).  The prefill
    chunk attends only to itself, so the training flash kernel applies —
    a pallas_call cannot be GSPMD-partitioned, but on the data + tp
    meshes of the ``cache_specs``/``paged_cache_specs`` layouts it runs
    per shard under a shard_map, skipping the einsum's O(t^2)
    materialized score tensor.  Shape/mesh gates run BEFORE the backend
    check so they stay testable off-TPU; t must tile (multiple of 8)
    and be big enough to beat the einsum's fixed cost.  Monkeypatch
    point for CPU tests (interpret mode)."""
    if t % 8 or t < 128:
        return None
    if not _shard_map_mesh_ok(cfg, mesh, batch, need_n_heads_div=True):
        return None
    if jax.default_backend() != "tpu":
        return None
    return {}


def _block_decode(cfg: TransformerConfig, x, lp, ck, cv, li, positions,
                  pos, sharded: bool = False, mesh: Optional[Mesh] = None,
                  pages=None):
    """One block over a token chunk with cached history.

    ``x``: [B, t, d] (t = chunk length; 1 in steady-state decode);
    ``ck``/``cv``: the STACKED cache ([L, B, KV, M, Dh], or the paged
    pool [L, P, KV, page, Dh]) carried through the layer scan, with
    ``li`` this block's layer index — writes update one slot in place at
    the index and the kernels read O(pos) at the index through their
    scalar prefetch, so the full buffer is never restacked or sliced;
    ``positions``: [B, t] per-row global positions of the chunk (rows
    differ in the ragged case); ``pos``: first chunk position — scalar
    (python int or traced) or [B] vector, as handed to ``_cache_write``.
    A multi-token prefill from an empty cache attends chunk-to-chunk (flash
    kernel when ``sharded=False``; a plain einsum when ``sharded=True`` so
    GSPMD can partition it — a pallas_call under sharded jit cannot be).
    Steady-state (t=1) queries take the flash-decode kernel when
    ``_decode_kernel_kwargs`` opens the gate — directly, or per shard via
    ``sharded_flash_decode`` when a mesh is given — and otherwise fall to
    the dense einsum over the cache with an offset causal mask.
    """
    b, t, _ = x.shape
    m = _cache_logical_len(ck, pages)
    h = rms_norm(x, lp["attn_norm"].astype(cfg.dtype))
    q = _qmm(h, lp["wq"], cfg.dtype).reshape(b, t, cfg.n_heads,
                                             cfg.head_dim)
    k = _qmm(h, lp["wk"], cfg.dtype).reshape(b, t, cfg.kv_heads,
                                             cfg.head_dim)
    v = _qmm(h, lp["wv"], cfg.dtype).reshape(b, t, cfg.kv_heads,
                                             cfg.head_dim)
    pos_row = positions                                 # [b, t]
    q = rope(q, pos_row, cfg.rope_theta)
    k = rope(k, pos_row, cfg.rope_theta)
    rolling = cfg.window is not None
    self_attn_prefill = t > 1 and isinstance(pos, int) and pos == 0
    o_paged = None
    # Single-host paged steps DEFER their pool commit: one XLA scatter
    # costs ~0.5 ms regardless of size (measured, v5e), so the
    # per-layer write-then-attend order would spend 2L scatters per
    # step.  Instead the chunk rides into attention as a SELF operand
    # (kernel: a [head_block, t, d] block accumulated at the last page
    # step, causal across the chunk's own tokens; reference: written
    # into the gathered view) and decode_step commits ALL layers'
    # chunks in one scatter per pool leaf after the scan.  t > 1 is the
    # fused multi-row step (speculative verify / chunked-prefill
    # tails): t rows retire through ONE attention launch per layer and
    # one commit pair per dispatch, instead of per-layer write-then-
    # attend scatters.
    defer = pages is not None and not sharded
    if pages is not None and sharded:
        # Multi-chip serving: write + paged attention per shard (the page
        # indirection cannot be GSPMD-partitioned; everything around it
        # stays plain einsums).  Prefill-from-empty writes in the island
        # and attends chunk-to-chunk outside it.
        o_paged, ck, cv = _sharded_paged_step(
            cfg, mesh, q, k, v, ck, cv, li, pages, positions,
            attend=not self_attn_prefill)
    elif pages is not None:
        pass    # single-host paged: deferred — decode_step commits
    else:
        ck = _cache_write(ck, k, li, pos, rolling=rolling)
        cv = _cache_write(cv, v, li, pos, rolling=rolling)
    kv = cfg.kv_heads
    g = cfg.n_heads // kv
    if t > 1 and isinstance(pos, int) and pos == 0:
        # Prefill from an empty cache: the chunk only attends to itself —
        # [t, t] instead of a [t, M] score tensor over the (mostly empty)
        # cache.  GQA stays at kv width (both impls group internally).
        if sharded:
            pkw = _prefill_kernel_kwargs(cfg, mesh, b, t)
            if pkw is not None:
                # data x tp mesh: the flash kernel per shard (shard_map)
                # instead of the einsum's O(t^2) materialized scores.
                from tfmesos_tpu.ops.attention import \
                    sharded_flash_attention
                o = sharded_flash_attention(q, k, v, mesh, causal=True,
                                            window=cfg.window, **pkw)
            else:
                o = mha_reference(q, k, v, causal=True, window=cfg.window)
        else:
            o = attend(q, k, v, mesh=None, causal=True, window=cfg.window)
    elif o_paged is not None:
        o = o_paged
    elif pages is not None:
        # Paged attention: pool-page indirection through the kernel's
        # scalar-prefetched index maps (TPU), or the gather-the-pages
        # reference elsewhere.  Single-host path (the pool gather does
        # not GSPMD-partition).
        from tfmesos_tpu.ops.attention import (_paged_decode_reference,
                                               flash_decode_paged)
        self_kv = None
        if defer:
            # int8 pools: quantize-dequantize the chunk so the self
            # operand matches a committed slot up to rounding — the
            # kernel folds a committed slot's fp32 scale into the
            # probability row post-dot, while the self operand rides in
            # pre-multiplied, so the two orderings can differ in the
            # last float ulp even though the int8 values and scales are
            # identical.
            if isinstance(ck, QTensor):
                from tfmesos_tpu.ops.quant import quantize_int8_reference
                rq = lambda c: (lambda v_, s_: (v_.astype(cfg.dtype)
                                                * s_.astype(cfg.dtype)))(
                    *quantize_int8_reference(c))
                self_kv = (rq(k), rq(v))
            else:
                self_kv = (k, v)
        kw = _decode_kernel_kwargs(cfg, m, t, False)
        if kw is not None:
            o = flash_decode_paged(q, ck, cv, pages, positions[:, 0],
                                   layer=li, self_kv=self_kv, **kw)
        else:
            o = _paged_decode_reference(
                q, ck, cv, pages, positions[:, 0],
                1.0 / math.sqrt(cfg.head_dim), layer=li, self_kv=self_kv)
    elif (kernel_kw := _decode_kernel_kwargs(cfg, m, t, sharded, mesh,
                                             batch=b)) is not None:
        # Cache-bounded flash-decode kernel (t=1 steps and short chunks —
        # speculative verify / chunked prefill): scalar-prefetched block
        # bound caps per-step HBM traffic at O(pos) cache slots instead of
        # the full buffer, independently per row
        # (ops/attention.flash_decode).  Under sharded decode with an
        # explicit mesh it runs per shard via shard_map (batch + kv-major
        # tp head blocks).
        if sharded:
            from tfmesos_tpu.ops.attention import sharded_flash_decode
            o = sharded_flash_decode(q, ck, cv, positions[:, 0], mesh,
                                     layer=li, **kernel_kw)
        else:
            from tfmesos_tpu.ops.attention import flash_decode
            o = flash_decode(q, ck, cv, positions[:, 0], layer=li,
                             **kernel_kw)
    else:
        # Grouped einsum over this layer's cache slice: the KV blocks
        # stream from HBM once at kv_heads width (int8 when quantized) —
        # never materialized at n_heads.
        ck_r = _cache_read(ck, li, cfg.dtype)
        cv_r = _cache_read(cv, li, cfg.dtype)
        q5 = q.reshape(b, t, kv, g, cfg.head_dim)
        s = jnp.einsum("btkgd,bkmd->bkgtm", q5, ck_r).astype(jnp.float32)
        s = s / math.sqrt(cfg.head_dim)
        if cfg.window is not None:
            # Rolling cache: slot j holds global position p - ((p - j) % M)
            # (the latest position congruent to j not after p).  Negative
            # slot positions are not yet written; everything resident is
            # within the window when M == window.
            if t > 1:
                raise ValueError("chunked decode over a rolling windowed "
                                 "cache is not supported; decode one token "
                                 "per step after the prefill")
            p0 = positions[0, 0]    # rolling caches are never ragged
            slot = jax.lax.broadcasted_iota(jnp.int32, (t, m), 1)
            spos = p0 - ((p0 - slot) % m)
            bad = (spos < 0) | (spos < p0 - (cfg.window - 1))
            bad = bad[None]
        else:
            kpos = jax.lax.broadcasted_iota(jnp.int32, (t, m), 1)
            bad = kpos[None] > positions[:, :, None]    # [b, t, m]
        s = jnp.where(bad[:, None, None], -jnp.inf, s)
        probs = jax.nn.softmax(s, axis=-1).astype(cv_r.dtype)
        o = jnp.einsum("bkgtm,bkmd->btkgd", probs, cv_r)
    x = x + _qmm(o.reshape(b, t, -1), lp["wo"], cfg.dtype)
    h = rms_norm(x, lp["mlp_norm"].astype(cfg.dtype))
    ffn, _ = _ffn(cfg, None, lp, h)
    return x + ffn, ck, cv, ((k, v) if defer else None)


def decode_step(cfg: TransformerConfig, params, cache, tokens, pos,
                sharded: bool = False, mesh: Optional[Mesh] = None):
    """Advance decoding by a token chunk.

    ``tokens``: [B, t] (the prompt at prefill, one token per step after);
    ``pos``: first global position of the chunk (python int or traced), or
    a [B] int32 vector for RAGGED batches — each row decodes at its own
    position (mixed-length serving: cache writes, attention bounds, and
    rope all follow the per-row position; not with windowed configs).
    Returns (logits [B, t, V], updated cache).

    For multi-chip decode, pass ``sharded=True``, place the params per
    ``partition_specs`` and the cache per ``cache_specs``, and jit: every
    op is then a plain einsum GSPMD can partition (batch over the data
    axes, heads over tp).  ``sharded=False`` (the ``generate`` path) may
    use the Pallas flash kernel for the prefill chunk instead.  sp and pp
    are training-side axes with no decode analogue here.

    Passing the ``mesh`` alongside ``sharded=True`` additionally lets
    single-token steps AND short chunks (speculative verify / chunked
    prefill) run the flash-decode kernel per shard (shard_map over the
    ``cache_specs`` layout: batch axes + tp head blocks) — O(pos)-bounded
    cache reads on every chip; without a mesh, or when the batch does not
    divide over the data axes, the sharded path keeps the plain einsum.

    Exactness contract: dense and dense-MoE configs reproduce ``forward()``
    logits position by position to numerical tolerance (the two paths use
    different attention accumulation orders).  Capacity-based switch MoE
    routes per chunk (tokens only compete within one ``decode_step`` call),
    so decode matches the training-time forward only up to capacity
    overflow — exact whenever nothing overflows, which per-token steps
    (n = B tokens) essentially never do.  That is the standard trade:
    dropping tokens by batch-order competition at inference would be worse
    than the mismatch.
    """
    b, t = tokens.shape
    x = _embed_lookup(params["embed"], tokens, cfg.dtype)
    ragged = getattr(pos, "ndim", 0) == 1
    if ragged and cfg.window is not None:
        raise ValueError("ragged positions do not compose with "
                         "sliding-window (rolling-cache) configs")
    offs = jnp.arange(t, dtype=jnp.int32)
    pos_arr = jnp.asarray(pos, jnp.int32)
    positions = jnp.broadcast_to(
        (pos_arr[:, None] if ragged else pos_arr) + offs, (b, t))

    pages = cache.get("pages")
    if pages is not None and sharded:
        # Multi-chip paged serving: pool placed per paged_cache_specs
        # (pages over the data axes with shard-local table ids, kv heads
        # over tp); validated once here, executed per layer as a
        # shard_map island (_sharded_paged_step).
        n_pool = (cache["k"].values if isinstance(cache["k"], QTensor)
                  else cache["k"]).shape[1]
        _check_sharded_paged(cfg, mesh, b, n_pool)

    # The cache is the scan CARRY, not xs/ys: each layer writes its token
    # slot in place at its index and the attention kernels read O(pos) at
    # the index.  Scanning the cache through xs/ys instead would restack
    # the ENTIRE [L, ...] buffer every step — ~2 GB of HBM traffic per
    # token at max_len=16k, an order of magnitude over the einsum's own
    # read cost (measured round 5).
    def body(carry, layer):
        x, ck, cv = carry
        li, lp = layer
        x, ck, cv, chunks = _block_decode(cfg, x, lp, ck, cv, li,
                                          positions, pos, sharded=sharded,
                                          mesh=mesh, pages=pages)
        return (x, ck, cv), chunks

    # Long-buffer decode gains ~40% from a 2-wide unroll (cross-layer DMA
    # overlap; 1759 -> 2497 tok/s at max_len=16k on the v5e) while short
    # buffers LOSE ~6% to it and m=4k is a wash — gate on the static
    # buffer length.  unroll=4 loses the win again (VMEM pressure).
    (x, new_k, new_v), chunks = jax.lax.scan(
        body, (x, cache["k"], cache["v"]),
        (jnp.arange(cfg.n_layers, dtype=jnp.int32), params["layers"]),
        unroll=2 if _cache_logical_len(cache["k"], pages) >= 8192 else 1)
    if chunks is not None:
        # Deferred single-token paged writes (see _block_decode): commit
        # every layer's chunk in one scatter per pool leaf.
        new_k = _paged_cache_write_all(new_k, chunks[0], pages, pos)
        new_v = _paged_cache_write_all(new_v, chunks[1], pages, pos)
    x = rms_norm(x, params["norm_f"].astype(cfg.dtype))
    logits = _qmm(x, params["head"], cfg.dtype)
    out_cache = {"k": new_k, "v": new_v}
    if pages is not None:
        out_cache["pages"] = pages
    return logits, out_cache


def _check_sampling_args(top_k: Optional[int], top_p: Optional[float]):
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def filter_logits(logits, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Temperature-scale ``logits`` [..., V] and mask everything outside
    the ``top_k`` highest-logit tokens and/or the ``top_p`` nucleus (the
    smallest set of tokens whose probability mass reaches ``top_p``; the
    argmax token always survives) to -inf.  ``softmax`` of the result is
    the sampling distribution — exposed separately because speculative
    sampling needs the full distribution, not just a draw.  Requires
    ``temperature > 0``.  Static shapes throughout — sorts and masks, no
    dynamic gathers — so it scans/jits cleanly.
    """
    if temperature <= 0.0:
        raise ValueError("filter_logits needs temperature > 0 (greedy "
                         "sampling has no distribution to filter)")
    _check_sampling_args(top_k, top_p)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # Keep tokens whose PRECEDING cumulative mass is < top_p (the
        # first excluded token is the one that pushes the mass past it);
        # the argmax's preceding mass is 0, so it always survives.
        keep = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        threshold = jnp.min(jnp.where(keep, sorted_logits, jnp.inf),
                            axis=-1, keepdims=True)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def sample_logits(logits, key, temperature: float = 1.0,
                  top_k: Optional[int] = None,
                  top_p: Optional[float] = None):
    """Sample token ids from ``logits`` [..., V]: greedy when
    ``temperature <= 0``, else a categorical draw from
    ``filter_logits`` (temperature / top-k / top-p nucleus)."""
    if temperature <= 0.0:
        _check_sampling_args(top_k, top_p)
        return jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(
            jnp.int32)
    filtered = filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


def generate(cfg: TransformerConfig, params, prompt, max_new_tokens: int,
             rng=None, temperature: float = 0.0,
             top_k: Optional[int] = None, top_p: Optional[float] = None,
             quantized_cache: bool = False, prompt_lens=None,
             prefix=None, stop_token: Optional[int] = None, cache=None):
    """Autoregressive generation: prefill the prompt in one pass, then one
    fused scan step per token (KV cache; greedy, temperature, top-k and/or
    top-p nucleus sampling — see ``sample_logits``).

    ``quantized_cache`` stores K/V as int8 (``init_cache``) — combined
    with ``quantize_params`` this is the full int8 serving config.

    ``prompt``: [B, Tp] int32.  Returns [B, Tp + max_new_tokens]
    (``[B, T0 + Tp + max_new_tokens]`` with a prefix).

    ``prompt_lens`` ([B] int32, optional) serves a RAGGED batch: row i's
    real prompt is ``prompt[i, :prompt_lens[i]]`` (right-padding ignored —
    causal attention plus per-row position bounds keep pad slots
    invisible, and each row's generated tokens overwrite them in the
    cache).  Row i's continuation lands right after its real prompt in
    the returned array; later entries are padding.

    ``prefix`` ([T0] int32, optional) is a SHARED prompt prefix (system
    prompt): prefilled ONCE at batch 1 and its cache broadcast to every
    row — the prompt-caching serving pattern.  Equivalent to prepending
    it to every row of ``prompt``, at 1/B the prefix prefill cost.

    ``stop_token``: rows that emit it freeze (their tail fills with the
    stop token), and decoding EXITS EARLY once every row has stopped —
    tokens up to each row's first stop are identical to a run without
    ``stop_token``.

    ``cache``: a caller-managed cache — notably a PAGED one
    (``init_paged_cache`` + a :class:`PageAllocator` table under
    ``"pages"``), whose pages must back every position the run touches.
    """
    b, tp = prompt.shape
    t0 = 0 if prefix is None else prefix.shape[0]
    if max_new_tokens <= 0:
        # Keep the documented [B, T0 + Tp] shape in the degenerate case.
        if prefix is None:
            return prompt
        return jnp.concatenate(
            [jnp.broadcast_to(prefix, (b, t0)), prompt], axis=1)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(logits, key):
        return sample_logits(logits, key, temperature, top_k, top_p)

    logits, cache = _prefill(cfg, params, prompt, t0 + tp + max_new_tokens,
                             quantized=quantized_cache, prefix=prefix,
                             cache=cache)
    rng, key = jax.random.split(rng)
    if prompt_lens is None:
        next_logits = logits[:, -1]
        pos0 = jnp.asarray(t0 + tp, jnp.int32)
    else:
        lens = jnp.asarray(prompt_lens, jnp.int32)
        # Row i's next token follows its LAST REAL token, not the padding.
        next_logits = jnp.take_along_axis(
            logits, (lens - 1)[:, None, None], axis=1)[:, 0]
        pos0 = t0 + lens
    tok = sample(next_logits, key)

    def step_once(cache, tok, pos, rng):
        logits, cache = decode_step(cfg, params, cache, tok[:, None], pos)
        rng, key = jax.random.split(rng)
        return cache, sample(logits[:, -1], key), rng

    if stop_token is None:
        def body(carry, _):
            cache, tok, pos, rng = carry
            cache, nxt, rng = step_once(cache, tok, pos, rng)
            return (cache, nxt, pos + 1, rng), tok

        (cache, tok, _, _), toks = jax.lax.scan(
            body, (cache, tok, pos0, rng), None,
            length=max_new_tokens - 1)
        generated = jnp.concatenate(
            [jnp.moveaxis(toks, 0, 1), tok[:, None]], axis=1)
    else:
        # while_loop instead of scan: exit as soon as every row stopped
        # (short answers don't pay for max_new_tokens steps).  The REAL
        # sampled token keeps feeding the model — only the recorded
        # output freezes — so cache/RNG state stays bit-identical to a
        # stop-free run and the before-the-stop equality guarantee is
        # unconditional (frozen rows feeding synthetic stop tokens could
        # otherwise perturb batch statistics, e.g. capacity-MoE routing).
        stop = jnp.asarray(stop_token, jnp.int32)
        gen0 = jnp.full((b, max_new_tokens), stop, jnp.int32)
        gen0 = jax.lax.dynamic_update_slice(gen0, tok[:, None], (0, 0))
        done0 = tok == stop

        def cond(state):
            i = state[4]
            return (i < max_new_tokens - 1) & ~jnp.all(state[5])

        def wbody(state):
            cache, tok, pos, rng, i, done, gen = state
            cache, nxt, rng = step_once(cache, tok, pos, rng)
            rec = jnp.where(done, stop, nxt)
            gen = jax.lax.dynamic_update_slice(gen, rec[:, None], (0, i + 1))
            return (cache, nxt, pos + 1, rng, i + 1, done | (nxt == stop),
                    gen)

        state = (cache, tok, pos0, rng, jnp.asarray(0, jnp.int32), done0,
                 gen0)
        state = jax.lax.while_loop(cond, wbody, state)
        generated = state[6]
    lead = (jnp.broadcast_to(prefix, (b, t0)),) if prefix is not None else ()
    if prompt_lens is None:
        return jnp.concatenate([*lead, prompt, generated], axis=1)
    # Scatter each row's continuation right after its real prompt.
    out = jnp.concatenate(
        [*lead, prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)],
        axis=1)
    idx = (t0 + lens)[:, None] + jnp.arange(max_new_tokens,
                                            dtype=jnp.int32)[None]
    return _scatter_rows(out, idx, generated)


def _prefill(cfg: TransformerConfig, params, prompt, depth: int,
             quantized: bool = False, prefix=None, cache=None):
    """Fresh-cache prefill shared by the generation entry points: with a
    ``prefix``, prefill it ONCE at batch 1, broadcast the cache to the
    prompt's batch (the cache batch axis is 1), then prefill the per-row
    prompt chunk at position t0.  Returns (prompt-chunk logits, cache).

    ``cache`` (optional) supplies a caller-managed cache instead — a
    preallocated contiguous one, or a PAGED dict ({"k", "v", "pages"};
    the caller's allocator must back every position the generation will
    touch).  Not combinable with ``prefix`` (whose batch-1 broadcast
    assumes this function owns the buffer)."""
    b = prompt.shape[0]
    if cache is not None:
        if prefix is not None:
            raise ValueError("generate: prefix and a caller-provided "
                             "cache cannot combine (the prefix broadcast "
                             "owns the buffer layout)")
        return decode_step(cfg, params, cache, prompt, 0)
    cache = init_cache(cfg, 1 if prefix is not None else b, depth,
                       quantized=quantized)
    if prefix is None:
        return decode_step(cfg, params, cache, prompt, 0)
    _, cache = decode_step(cfg, params, cache, prefix[None, :], 0)
    cache = jax.tree_util.tree_map(lambda x: jnp.repeat(x, b, axis=1),
                                   cache)
    return decode_step(cfg, params, cache, prompt, prefix.shape[0])


def _scatter_rows(out, idx, vals, mode: Optional[str] = None):
    """Row-wise scatter: ``out[i, idx[i]] = vals[i]`` (idx/vals may carry a
    trailing per-row dim).  ``mode="drop"`` discards out-of-bounds entries
    — the masked-write idiom (duplicate clipped indices have no defined
    scatter winner, so masking via OOB indices is the safe form)."""
    return jax.vmap(lambda o, i, v: o.at[i].set(v, mode=mode))(
        out, idx, vals)


def beam_search(cfg: TransformerConfig, params, prompt,
                max_new_tokens: int, beam: int = 4,
                quantized_cache: bool = False, return_scores: bool = False):
    """Deterministic beam search: keep the ``beam`` highest-total-logprob
    continuations, expanding all of them each step in one batched decode
    (the cache carries B·W rows; parent rows are gathered when beams
    reorder).  Returns the best sequence per row, [B, Tp + new] (with the
    per-row best total logprob when ``return_scores``).

    ``beam=1`` reduces to greedy decoding exactly.  Uniform prompts only
    (compose with ragged serving by bucketing lengths).
    """
    b, tp = prompt.shape
    w = int(beam)
    if w < 1:
        raise ValueError(f"beam must be >= 1, got {beam}")
    if max_new_tokens <= 0:
        return (prompt, jnp.zeros((b,), jnp.float32)) if return_scores \
            else prompt
    depth = tp + max_new_tokens
    cache = init_cache(cfg, b, depth, quantized=quantized_cache)
    logits, cache = decode_step(cfg, params, cache, prompt, 0)
    logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), -1)

    # First expansion: top-W tokens of the prefill distribution seed the
    # beams (no duplicate-beam trick needed — beams differ from step 0).
    scores, tok = jax.lax.top_k(logp0, w)               # [B, W]
    tok = tok.astype(jnp.int32)
    # Tile the cache W times: rows grouped beam-major per batch row
    # ([b0w0, b0w1, ..., b1w0, ...]) so row index = b*W + w.
    cache = jax.tree_util.tree_map(lambda x: jnp.repeat(x, w, axis=1),
                                   cache)
    hist = jnp.zeros((b, w, max_new_tokens), jnp.int32)
    hist = hist.at[:, :, 0].set(tok)

    def step(carry, i):
        cache, tok, scores, hist = carry
        logits, cache = decode_step(cfg, params, cache,
                                    tok.reshape(b * w, 1), tp + i)
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), -1)      # [B*W, V]
        v = logp.shape[-1]
        total = scores[:, :, None] + logp.reshape(b, w, v)
        scores, flat = jax.lax.top_k(total.reshape(b, w * v), w)
        parent = flat // v                              # [B, W]
        tok = (flat % v).astype(jnp.int32)
        # Reorder beam state to follow the surviving parents.
        rows = (jnp.arange(b, dtype=jnp.int32)[:, None] * w
                + parent).reshape(-1)                   # [B*W] global rows
        cache = jax.tree_util.tree_map(
            lambda c: jnp.take(c, rows, axis=1), cache)
        hist = jnp.take_along_axis(hist, parent[:, :, None], axis=1)
        hist = jax.lax.dynamic_update_index_in_dim(
            hist, tok, i + 1, axis=2)
        return (cache, tok, scores, hist), None

    (cache, tok, scores, hist), _ = jax.lax.scan(
        step, (cache, tok, scores, hist),
        jnp.arange(max_new_tokens - 1, dtype=jnp.int32))
    best = jnp.argmax(scores, axis=1)                   # [B]
    best_hist = jnp.take_along_axis(
        hist, best[:, None, None], axis=1)[:, 0]        # [B, new]
    out = jnp.concatenate([prompt, best_hist], axis=1)
    if return_scores:
        return out, jnp.take_along_axis(scores, best[:, None], 1)[:, 0]
    return out


def greedy_accept_counts(drafts, g):
    """Greedy speculative acceptance: given draft proposals [B, k] and
    the target's greedy tokens over the verify chunk [B, k+1], return
    the per-row commit count — the leading run of draft==target matches
    plus one (the target's correction, or its bonus token when every
    proposal matched).  Shared by ``speculative_generate`` and the
    continuous batcher's speculative rounds (the subtle bit is the
    argmin-over-[match|False] form: it returns the FIRST mismatch index,
    or k when there is none)."""
    k = drafts.shape[1]
    match = drafts == g[:, :k]
    a = jnp.argmin(jnp.concatenate(
        [match, jnp.zeros((match.shape[0], 1), bool)],
        axis=1).astype(jnp.int32), axis=1)
    return a + 1


def rejection_accept(drafts, pd, pt, u):
    """Speculative rejection sampling's accept/correct math, shared by
    ``speculative_generate`` and the continuous batcher's sampled rounds.

    ``drafts`` [B, k] proposals, ``pd`` [B, k, V] their draft
    distributions, ``pt`` [B, k+1, V] the target's (filtered)
    distributions over the verify chunk, ``u`` [B, k] uniform draws.
    Accept proposal j iff ``u_j < pt(x_j)/pd(x_j)`` (computed as
    ``u*pd < pt``, robust as pd → 0); ``a`` is the first rejection index
    (k when all accepted).  Returns ``(a, dist)`` where ``dist`` [B, V]
    is the correction distribution at index a — norm(max(0, pt − pd)),
    with pd zero-padded at index k so the all-accepted bonus draw (from
    pt_k itself) falls out of the same formula."""
    b, k = drafts.shape
    ptx = jnp.take_along_axis(pt[:, :k], drafts[..., None], -1)[..., 0]
    pdx = jnp.take_along_axis(pd, drafts[..., None], -1)[..., 0]
    acc = u * pdx < ptx
    a = jnp.argmin(jnp.concatenate(
        [acc, jnp.zeros((b, 1), bool)], axis=1).astype(jnp.int32), axis=1)
    pd_pad = jnp.concatenate(
        [pd, jnp.zeros((b, 1, pd.shape[-1]), pd.dtype)], axis=1)
    pt_a = jnp.take_along_axis(pt, a[:, None, None], 1)[:, 0]
    pd_a = jnp.take_along_axis(pd_pad, a[:, None, None], 1)[:, 0]
    resid = jnp.maximum(pt_a - pd_a, 0.0)
    norm = jnp.sum(resid, -1, keepdims=True)
    dist = jnp.where(norm > 1e-9, resid / jnp.maximum(norm, 1e-9), pt_a)
    return a, dist


def speculative_cache_depth(prompt_len: int, max_new_tokens: int,
                            n_draft: int, prefix_len: int = 0) -> int:
    """Cache positions ``speculative_generate`` may touch (its overshoot
    slack included): size contiguous caches — or back paged rows
    (``PageAllocator.ensure``) — with AT LEAST this many positions."""
    return prefix_len + prompt_len + max_new_tokens + 2 * n_draft + 1


def speculative_generate(cfg: TransformerConfig, params,
                         draft_cfg: TransformerConfig, draft_params,
                         prompt, max_new_tokens: int, n_draft: int = 4,
                         prompt_lens=None, temperature: float = 0.0,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None, rng=None,
                         quantized_cache: bool = False, prefix=None,
                         cache=None, stop_token: Optional[int] = None):
    """Speculative decoding: a cheap DRAFT model proposes ``n_draft``
    tokens per round, the target model scores them all in ONE chunked
    decode, and the leading accepted run commits (plus one
    correction/bonus token) — between 1 and ``n_draft + 1`` tokens per
    target dispatch.

    ``temperature <= 0`` (default): greedy — a draft commits while it
    matches the target's own argmax, and the output is EXACTLY the target
    model's greedy continuation, whatever the draft proposes (a bad draft
    only costs speed).  ``temperature > 0``: speculative SAMPLING
    (Leviathan et al.) — draft token x is accepted with probability
    ``min(1, p_target(x)/p_draft(x))``; on rejection the correction is
    drawn from ``norm(max(0, p_target − p_draft))``, and when every draft
    survives a bonus token is drawn from the target's next distribution.
    The committed tokens are distributed exactly as target-only sampling
    under the same temperature/top-k/top-p filtering.

    Both models run on the ragged per-row position machinery, so each
    batch row accepts at its own rate.  ``prompt``: [B, Tp];
    ``prompt_lens`` and ``prefix`` as in :func:`generate` (a shared
    prefix prefills ONCE per model at batch 1 and broadcasts into both
    caches).  Returns [B, (T0 +) Tp + max_new_tokens] with row i's
    continuation right after its real prompt.

    ``cache``: a caller-managed TARGET cache (e.g. a paged pool); it
    must back at least :func:`speculative_cache_depth` positions per
    row.  ``stop_token``: rows freeze once a committed token is the
    stop and the loop exits when all rows have stopped; tokens up to
    each row's FIRST stop equal a stop-free run, but — unlike
    :func:`generate`, which fills the tail with the stop token — the
    tail after the stop is UNSPECIFIED (same-round overshoot tokens,
    then zeros); truncate at the first stop as ``examples/serve.py``
    does.
    """
    if cfg.window is not None or draft_cfg.window is not None:
        raise ValueError("speculative decoding does not compose with "
                         "sliding-window configs (rolling caches cannot "
                         "be ragged)")
    b, tp = prompt.shape
    if max_new_tokens <= 0:
        # Keep the documented [B, T0 + Tp] shape in the degenerate case.
        if prefix is None:
            return prompt
        return jnp.concatenate(
            [jnp.broadcast_to(prefix, (b, prefix.shape[0])), prompt],
            axis=1)
    k = int(n_draft)
    if k < 1:
        raise ValueError(f"n_draft must be >= 1, got {n_draft}")
    sampling = temperature > 0.0
    if rng is None:
        rng = jax.random.PRNGKey(0)
    t0 = 0 if prefix is None else prefix.shape[0]
    # Slack: a row can overshoot to committed = max_new + k (pos =
    # lens + max_new + k - 1) and, frozen, keeps verifying k+1-token
    # chunks at that position — writes reach lens + max_new + 2k.
    depth = speculative_cache_depth(tp, max_new_tokens, k, prefix_len=t0)
    # ``quantized_cache``/caller-provided ``cache`` (e.g. a paged pool —
    # its pages must back depth-many positions) apply to the TARGET cache
    # (where the bytes are); the draft is small by construction and stays
    # an internal fp buffer.
    logits, cache = _prefill(cfg, params, prompt, depth,
                             quantized=quantized_cache, prefix=prefix,
                             cache=cache)
    _, draft_cache = _prefill(draft_cfg, draft_params, prompt, depth,
                              prefix=prefix)
    if prompt_lens is None:
        lens = jnp.full((b,), tp, jnp.int32)
    else:
        lens = jnp.asarray(prompt_lens, jnp.int32)
    first_logits = jnp.take_along_axis(
        logits, (lens - 1)[:, None, None], axis=1)[:, 0]
    rng, key0 = jax.random.split(rng)
    tok = sample_logits(first_logits, key0, temperature, top_k, top_p)
    lens = t0 + lens                    # absolute positions from here on
    # One committed token exists already (the prefill's sample).
    lead = (jnp.broadcast_to(prefix, (b, t0)),) if prefix is not None else ()
    out = jnp.concatenate(
        [*lead, prompt, jnp.zeros((b, max_new_tokens), prompt.dtype)],
        axis=1)
    out = _scatter_rows(out, lens, tok)
    limit = lens + max_new_tokens       # first out index past row's region

    def commit(out, pos, n_commit, vals):
        # Commit the first n_commit vals right after each row's last
        # committed token.  Masked/overflow entries get an out-of-bounds
        # index and drop — clipping instead would alias real indices, and
        # duplicate scatter indices have no defined winner.
        j = jnp.arange(k + 1, dtype=jnp.int32)[None]
        idx = pos[:, None] + 1 + j
        mask = (j < n_commit[:, None]) & (idx < limit[:, None])
        return _scatter_rows(out, jnp.where(mask, idx, out.shape[1]), vals,
                             mode="drop")

    def advance(committed, n_commit, vals):
        # ``stop_token``: a row whose committed run contains the stop
        # freezes (its quota fills) — the loop exits once every row has
        # stopped.  Tokens after a row's first stop within the same
        # round's commit are unspecified; truncate at the stop (as
        # examples/serve.py does).
        nxt = committed + n_commit
        if stop_token is None:
            return nxt
        j = jnp.arange(k + 1, dtype=jnp.int32)[None]
        hit = jnp.any((vals == stop_token) & (j < n_commit[:, None]),
                      axis=1)
        return jnp.where(hit, max_new_tokens, nxt)

    def greedy_round(state):
        cache, draft_cache, tok, pos, committed, out, rng = state
        active = committed < max_new_tokens

        # Draft k tokens autoregressively (t=1 ragged steps).  k+1 scan
        # steps: the extra one writes the last proposal's K/V at pos+k
        # (proposal discarded), so a fully-accepted round never leaves a
        # hole the draft would condition on for the rest of the row.
        def dstep(carry, _):
            dcache, dtok, dpos = carry
            lg, dcache = decode_step(draft_cfg, draft_params, dcache,
                                     dtok[:, None], dpos)
            nxt = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)
            return (dcache, nxt, dpos + 1), nxt

        (draft_cache, _, _), drafts = jax.lax.scan(
            dstep, (draft_cache, tok, pos), None, length=k + 1)
        drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]      # [B, k]

        # Target scores the whole drafted chunk in one ragged decode.
        chunk = jnp.concatenate([tok[:, None], drafts], axis=1)  # [B, k+1]
        lg, cache = decode_step(cfg, params, cache, chunk, pos)
        g = jnp.argmax(lg, -1).astype(jnp.int32)        # [B, k+1] greedy
        counts = greedy_accept_counts(drafts, g)
        a = counts - 1                                  # leading-run length
        n_commit = jnp.where(active, counts, 0)
        out = commit(out, pos, n_commit, g)
        tok = jnp.where(active,
                        jnp.take_along_axis(g, a[:, None], axis=1)[:, 0],
                        tok)
        return (cache, draft_cache, tok, pos + n_commit,
                advance(committed, n_commit, g), out, rng)

    def sampling_round(state):
        cache, draft_cache, tok, pos, committed, out, rng = state
        active = committed < max_new_tokens
        rng, kd, ka, kr = jax.random.split(rng, 4)

        # Draft k sampled tokens, keeping each step's full distribution.
        def dstep(carry, key):
            dcache, dtok, dpos = carry
            lg, dcache = decode_step(draft_cfg, draft_params, dcache,
                                     dtok[:, None], dpos)
            f = filter_logits(lg[:, -1], temperature, top_k, top_p)
            nxt = jax.random.categorical(key, f, axis=-1).astype(jnp.int32)
            return (dcache, nxt, dpos + 1), (nxt, jax.nn.softmax(f, -1))

        # k+1 steps for the same backfill-the-last-slot reason as the
        # greedy round; the extra proposal and its distribution drop.
        (draft_cache, _, _), (drafts, pd) = jax.lax.scan(
            dstep, (draft_cache, tok, pos), jax.random.split(kd, k + 1))
        drafts = jnp.moveaxis(drafts, 0, 1)[:, :k]      # [B, k]
        pd = jnp.moveaxis(pd, 0, 1)[:, :k]              # [B, k, V]

        chunk = jnp.concatenate([tok[:, None], drafts], axis=1)
        lg, cache = decode_step(cfg, params, cache, chunk, pos)
        pt = jax.nn.softmax(
            filter_logits(lg, temperature, top_k, top_p), -1)  # [B, k+1, V]

        # Accept x_j with prob min(1, pt(x_j)/pd(x_j)); correct at the
        # first rejection from norm(max(0, pt − pd)) — rejection_accept
        # carries the shared math.
        u = jax.random.uniform(ka, (b, k))
        a, dist = rejection_accept(drafts, pd, pt, u)
        repl = jax.random.categorical(
            kr, jnp.log(dist + 1e-20), axis=-1).astype(jnp.int32)

        n_commit = jnp.where(active, a + 1, 0)
        j = jnp.arange(k + 1, dtype=jnp.int32)[None]
        cand = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
        vals = jnp.where(j == a[:, None], repl[:, None], cand)
        out = commit(out, pos, n_commit, vals)
        tok = jnp.where(active, repl, tok)
        return (cache, draft_cache, tok, pos + n_commit,
                advance(committed, n_commit, vals), out, rng)

    committed0 = jnp.ones((b,), jnp.int32)
    if stop_token is not None:
        committed0 = jnp.where(tok == stop_token, max_new_tokens,
                               committed0)
    state = (cache, draft_cache, tok, lens, committed0, out, rng)
    state = jax.lax.while_loop(
        lambda s: jnp.any(s[4] < max_new_tokens),
        sampling_round if sampling else greedy_round, state)
    return state[5]


def _fused_ce_mode(cfg: TransformerConfig, params, mesh: Optional[Mesh],
                   batch_size: Optional[int] = None) -> Optional[str]:
    """Which fused head+CE path ``loss_fn`` takes: "dense" (single device),
    "dp" (batch-sharded chunks on multi-device data-only meshes — the naive
    dense chunking would cut every chunk across the dp sharding), "tp"
    (vocab-parallel over the tp axis), or None (the standard
    materialize-the-logits path — sp shards the token dim the chunking
    would cut across, pp computes the loss outside the pipeline body, ep
    leaves activation replication to GSPMD)."""
    if isinstance(params["head"], QTensor):
        return None  # serving trees stay on the dequantize-at-matmul path
    if cfg.fused_ce is False:
        return None
    if mesh is None:
        return "dense"
    real = {a for a, s in mesh.shape.items() if s > 1}
    if not real:
        return "dense"
    if real <= {"dp", "fsdp"}:
        # The shard_map'd dp path needs the batch to divide over the data
        # axes (the GSPMD dense route didn't); fall back when it doesn't
        # (e.g. a final partial batch) or when the caller can't say.
        nd = 1
        for a in real:
            nd *= mesh.shape[a]
        if batch_size is not None and batch_size % nd == 0:
            return "dp"
        return "dense"
    if real <= {"dp", "fsdp", "tp"} and cfg.vocab_size % mesh.shape["tp"] == 0:
        return "tp"
    return "dense" if cfg.fused_ce else None


def loss_fn(cfg: TransformerConfig, params, batch, mesh: Optional[Mesh] = None):
    """Next-token prediction: batch = {"tokens": [B, T+1]}.

    With experts enabled, the router's auxiliary losses join the objective
    (standard switch-transformer weighting) and the realized token-overflow
    fraction is surfaced in the metrics."""
    tokens = batch["tokens"]
    mode = _fused_ce_mode(cfg, params, mesh, batch_size=tokens.shape[0])
    if mode is not None:
        x, aux = forward_hidden(cfg, params, tokens[:, :-1], mesh)
        # Pass the master-dtype head: the ops compute in x.dtype but
        # accumulate dw in fp32 and return it at the param dtype.
        if mode == "tp":
            loss = vocab_parallel_cross_entropy(
                x, params["head"], tokens[:, 1:], mesh,
                z_loss=cfg.z_loss, chunk=cfg.ce_chunk)
        elif mode == "dp":
            loss = data_parallel_fused_cross_entropy(
                x, params["head"], tokens[:, 1:], mesh,
                cfg.z_loss, cfg.ce_chunk)
        else:
            loss = fused_linear_cross_entropy(
                x, params["head"], tokens[:, 1:], z_loss=cfg.z_loss,
                chunk=cfg.ce_chunk)
    else:
        logits, aux = forward(cfg, params, tokens[:, :-1], mesh,
                              return_aux=True)
        loss = cross_entropy_loss(logits, tokens[:, 1:],
                                  z_loss=cfg.z_loss)
    metrics = {"perplexity": jnp.exp(loss)}
    if cfg.n_experts:
        # Under pp the aux rides the pipeline per microbatch (gpipe-style
        # estimator of the full-batch statistics); without pp it is the
        # exact batch statistic.  Either way it joins the objective.
        loss = (loss
                + cfg.router_aux_weight * aux["load_balance_loss"]
                + cfg.router_z_weight * aux["z_loss"])
        metrics.update(load_balance_loss=aux["load_balance_loss"],
                       router_z_loss=aux["z_loss"],
                       moe_overflow_frac=aux["overflow_frac"])
    return loss, metrics


def train_step_1f1b(cfg: TransformerConfig, params, batch,
                    mesh: Mesh, num_microbatches: Optional[int] = None):
    """One fused 1F1B forward+backward pass of the LM objective on a
    pp x dp/fsdp mesh: returns ``(loss, grads)`` with ``grads`` matching
    ``params``' structure (fp32), ready for any optax update.

    This is the memory-bounded alternative to ``jax.grad(loss_fn)`` over
    the gpipe/circular pipeline: the live activation stash is one chunk
    input per pipeline slot (O(pp), not O(microbatches)) because forward
    and backward interleave inside ``pipeline_train_1f1b``'s single loop.
    The embedding differentiates through the returned dx, and the final
    norm + unembedding head ride as tail params of the loss stage.

    Scope: dense AND dense-top-k-MoE configs on pp x tp x ep (+ dp/fsdp)
    meshes.  tp stages run the manual-collective Megatron block with the
    in-body-AD f/g collectives, and the loss tail is the in-body
    VOCAB-PARALLEL fused CE (``ops/layers.vocab_parallel_ce_inbody``:
    the unembedding shards over tp, no device holds more than a
    [chunk, V/tp] logits block — fwd or bwd); a vocab that does not
    divide over tp falls back to the replicated fused-CE tail, as
    ``loss_fn`` does.  MoE stages shard whole experts over ep (and
    per-expert FFN widths over tp) with the in-body-AD f/g collectives,
    and carry the router aux losses as per-stage scalar aux terms seeded
    alongside the loss vjp (``pipeline_train_1f1b(stage_aux=True)``) —
    the same layer-mean estimator the gpipe route uses, so grads match
    ``jax.grad(loss_fn)`` on the same mesh.  ``cfg.pp_virtual_stages > 1``
    runs the INTERLEAVED 1F1B timetable (device d owns layer chunks d,
    d+pp, ...; every microbatch laps the ring v times), shrinking the
    bubble for v x more ppermute hops at the same per-chunk stash rule.
    sp shards the SEQUENCE inside stages — composing with tp into the
    full pp x tp x sp x dp stack (local heads x local sequence):
    attention is the K/V all_gather form (``_sp_gather_attention`` — a
    ppermute ring's global participant set would deadlock in the tick's
    divergent branches), weights and the loss tail fan/reduce over sp
    with the f/g pair, and router aux averages per shard.
    ``moe_impl='switch'`` stays with the gpipe/circular schedules.
    """
    pp = mesh.shape.get("pp", 1)
    tp = mesh.shape.get("tp", 1)
    ep = mesh.shape.get("ep", 1)
    sp = mesh.shape.get("sp", 1)
    real = {a for a, s in mesh.shape.items() if s > 1}
    if not real <= {"pp", "tp", "dp", "fsdp", "ep", "sp"}:
        raise ValueError(
            f"train_step_1f1b supports pp x tp x ep x sp x dp/fsdp "
            f"meshes; got {dict(mesh.shape)}")
    if sp > 1 and (batch["tokens"].shape[1] - 1) % sp:
        raise ValueError(
            f"sequence length {batch['tokens'].shape[1] - 1} must divide "
            f"over sp ({sp})")
    if tp > 1 and cfg.kv_heads % tp:
        raise ValueError(f"1f1b x tp needs tp ({tp}) to divide kv_heads "
                         f"({cfg.kv_heads})")
    if tp > 1 and cfg.d_ff % tp:
        raise ValueError(f"1f1b x tp needs tp ({tp}) to divide d_ff "
                         f"({cfg.d_ff}) for the Megatron FFN split")
    if ep > 1 and not cfg.n_experts:
        raise ValueError("an ep axis needs n_experts > 0")
    if cfg.n_experts and cfg.n_experts % max(ep, 1):
        raise ValueError(f"ep ({ep}) must divide n_experts "
                         f"({cfg.n_experts})")
    if cfg.n_experts and cfg.moe_impl == "switch":
        raise ValueError("train_step_1f1b runs the dense top-k MoE "
                         "(moe_impl='switch' assumes outer "
                         "differentiation); use pp_schedule="
                         "'gpipe'/'circular' for switch dispatch")
    v = cfg.pp_virtual_stages
    if v > 1 and pp < 2:
        raise ValueError("pp_virtual_stages > 1 needs a real pp axis")
    n_chunks = max(pp, 1) * v
    if cfg.n_layers % n_chunks:
        raise ValueError(f"{cfg.n_layers} layers not divisible into "
                         f"{n_chunks} pipeline chunks "
                         f"({pp} stages x {v} virtual)")
    from tfmesos_tpu.parallel.pipeline import pipeline_train_1f1b

    tokens = batch["tokens"]
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    per = cfg.n_layers // n_chunks
    stacked = jax.tree_util.tree_map(
        lambda p: p.reshape(n_chunks, per, *p.shape[1:]),
        params["layers"])

    ep_axis = "ep" if (cfg.n_experts and ep > 1) else None
    sp_axis = "sp" if sp > 1 else None
    partition = None
    if tp > 1:
        # forward_hidden's dense tp partition table (shared helpers);
        # stages run the manual Megatron block with in-body-AD
        # collectives.
        partition = {**_dense_tp_attn_partition(),
                     **(_moe_param_partition(ep_axis, "tp")
                        if cfg.n_experts else _dense_tp_mlp_partition())}
        if cfg.n_shared_experts:
            partition.update(_shared_expert_partition("tp"))
    elif ep_axis:
        partition = {
            **_replicated_attn_partition(),
            **_moe_param_partition(ep_axis, None),
        }
        if cfg.n_shared_experts:
            partition.update(_shared_expert_partition(None))

    # MoE stages return a pre-weighted scalar aux loss (their layers'
    # summed router terms, normalized by n_layers so the sum over stages
    # is the model's layer-mean aux — the same estimator loss_fn's gpipe
    # route uses); pipeline_train_1f1b seeds it alongside the loss vjp.
    stage_aux = bool(cfg.n_experts)

    def stage_fn(stage_params, h):
        if sp_axis is not None:
            # Sequence shards: weights are REPLICATED over sp but consumed
            # by per-shard-divergent (local-token) compute — fan them
            # through the f operator so the in-body vjp psums their
            # partial gradients over sp exactly once.
            from tfmesos_tpu.parallel.collectives import (
                broadcast_replicated_grad)
            stage_params = jax.tree_util.tree_map(
                lambda w: broadcast_replicated_grad(w, sp_axis),
                stage_params)
        pos = jnp.arange(h.shape[1], dtype=jnp.int32)
        if sp_axis is not None:
            pos = pos + jax.lax.axis_index(sp_axis) * h.shape[1]
        pos = jnp.broadcast_to(pos, h.shape[:2])
        if tp > 1:
            body = lambda c, lp: _block_manual_tp(cfg, c, lp, pos,
                                                  ep_axis=ep_axis,
                                                  inbody_ad=True,
                                                  sp_axis=sp_axis)
        else:
            body = lambda c, lp: _block(cfg, None, c, lp, pos,
                                        ep_axis=ep_axis,
                                        inbody_ad=(ep_axis is not None
                                                   or sp_axis is not None),
                                        sp_axis=sp_axis)
        if cfg.remat:
            body = jax.checkpoint(body)
        out, layer_aux = jax.lax.scan(body, h, stage_params)
        if not stage_aux:
            return out
        aux = (cfg.router_aux_weight
               * jnp.sum(layer_aux["load_balance_loss"])
               + cfg.router_z_weight * jnp.sum(layer_aux["z_loss"])
               ) / cfg.n_layers
        if sp_axis is not None:
            # Per-shard (local-token) router statistics: average over sp
            # with the transpose-carrying reduction so the 1/m aux seed
            # flows back at 1/sp per shard, not sp-times over.
            from tfmesos_tpu.parallel.collectives import (
                psum_replicated_grad)
            aux = psum_replicated_grad(aux, sp_axis) / sp
        return out, aux.astype(jnp.float32)

    def tail_loss(tail, h, tgt_mb):
        # Fused head+CE: never materializes the [mb, T, vocab] logits —
        # the same bounded-memory route loss_fn takes, which matters
        # doubly on the schedule whose point is the O(pp) stash.  Under
        # tp the head arrives vocab-sharded and the in-body
        # vocab-parallel CE psums the softmax statistics explicitly
        # (its custom VJP keeps the in-loop backward collective-safe).
        # Under sp the tail weights fan (f operator) into per-shard
        # compute and the local-token mean reduces over sp with the
        # identity-transpose psum, so each shard's backward sees the
        # 1/sp-scaled seed exactly once.
        if sp_axis is not None:
            from tfmesos_tpu.parallel.collectives import (
                broadcast_replicated_grad, psum_replicated_grad)
            tail = jax.tree_util.tree_map(
                lambda w: broadcast_replicated_grad(w, sp_axis), tail)
        x = rms_norm(h, tail["norm_f"].astype(cfg.dtype))
        if vocab_parallel_tail:
            loss = vocab_parallel_ce_inbody(x, tail["head"], tgt_mb,
                                            "tp", cfg.z_loss,
                                            cfg.ce_chunk)
        else:
            loss = fused_linear_cross_entropy(x, tail["head"], tgt_mb,
                                              z_loss=cfg.z_loss,
                                              chunk=cfg.ce_chunk)
        if sp_axis is not None:
            loss = psum_replicated_grad(loss, sp_axis) / sp
        return loss

    x, vjp_embed = jax.vjp(
        lambda e: _embed_lookup(e, inp, cfg.dtype), params["embed"])
    tail = {"norm_f": params["norm_f"], "head": params["head"]}
    # Vocab-parallel tail only when the vocab divides over tp; otherwise
    # keep the replicated fused-CE tail (same fallback rule as
    # _fused_ce_mode's tp branch — an indivisible vocab must not refuse
    # a config the replicated tail trains fine).
    vocab_parallel_tail = tp > 1 and cfg.vocab_size % tp == 0
    tail_partition = ({"norm_f": P(None), "head": P(None, "tp")}
                      if vocab_parallel_tail else None)
    loss, g_stacked, g_tail, dx = pipeline_train_1f1b(
        stage_fn, tail_loss, stacked, x, tgt, mesh,
        num_microbatches=num_microbatches, tail_params=tail,
        param_partition=partition, tail_partition=tail_partition,
        stage_aux=stage_aux, virtual_stages=v, seq_axis=sp_axis)
    (g_embed,) = vjp_embed(dx.astype(x.dtype))
    grads = {
        "embed": jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), g_embed),
        "layers": jax.tree_util.tree_map(
            lambda g: g.reshape(cfg.n_layers, *g.shape[2:]), g_stacked),
        "norm_f": g_tail["norm_f"],
        "head": g_tail["head"],
    }
    return loss, grads


def _quantized_spec(s: P) -> QTensor:
    """The PartitionSpec pair for a QTensor leaf: ``values`` takes the
    weight's spec, ``scales`` the same minus the last dim (their trailing
    dim is 1, which cannot shard)."""
    parts = tuple(s)
    return QTensor(values=s,
                   scales=P(*(parts[:-1] + (None,))) if parts else P())


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop axes the mesh doesn't have (size-1 axes included)."""
    def keep(a):
        if a is None:
            return None
        if isinstance(a, (tuple, list)):
            kept = tuple(x for x in a if x in mesh.shape and mesh.shape[x] > 1)
            return kept if kept else None
        return a if a in mesh.shape and mesh.shape[a] > 1 else None
    return P(*(keep(a) for a in spec))


def partition_specs(cfg: TransformerConfig, mesh: Mesh) -> Dict[str, Any]:
    """PartitionSpec tree: Megatron-style tp, fsdp on the complementary dim,
    ep over experts.  The layer-stack dim (dim 0) is left unsharded here;
    the pp path re-shapes it into stages itself."""
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and (cfg.kv_heads * cfg.head_dim) % tp:
        raise ValueError(
            f"partition_specs: tp ({tp}) must divide the GQA kv projection "
            f"width ({cfg.kv_heads} kv heads x {cfg.head_dim})")
    layer = {
        "attn_norm": P(None, None),
        "wq": P(None, "fsdp", "tp"),
        "wk": P(None, "fsdp", "tp"),
        "wv": P(None, "fsdp", "tp"),
        "wo": P(None, "tp", "fsdp"),
        "mlp_norm": P(None, None),
    }
    if cfg.n_experts:
        layer.update(
            router=P(None, "fsdp", None),
            e_gate=P(None, "ep", "fsdp", "tp"),
            e_up=P(None, "ep", "fsdp", "tp"),
            e_down=P(None, "ep", "tp", "fsdp"),
        )
        if cfg.n_shared_experts:
            layer.update(
                s_gate=P(None, "fsdp", "tp"),
                s_up=P(None, "fsdp", "tp"),
                s_down=P(None, "tp", "fsdp"),
            )
    else:
        layer.update(
            w_gate=P(None, "fsdp", "tp"),
            w_up=P(None, "fsdp", "tp"),
            w_down=P(None, "tp", "fsdp"),
        )
    tree = {
        "embed": P("tp", "fsdp"),
        "layers": layer,
        "norm_f": P(None),
        "head": P("fsdp", "tp"),
    }
    return jax.tree_util.tree_map(
        lambda s: _filter_spec(s, mesh), tree,
        is_leaf=lambda s: isinstance(s, P))


def quantized_partition_specs(cfg: TransformerConfig, mesh: Mesh
                              ) -> Dict[str, Any]:
    """``partition_specs`` for a ``quantize_params`` tree: each quantized
    leaf becomes a QTensor of specs — ``values`` takes the weight's spec,
    ``scales`` the same minus the last dim (their trailing dim is 1, which
    cannot shard).  Place qparams with this and multi-chip sharded decode
    works exactly as with fp params (``decode_step(..., sharded=True)``).
    """
    specs = partition_specs(cfg, mesh)
    layers = {k: (_quantized_spec(v) if _quantizable(cfg, k) else v)
              for k, v in specs["layers"].items()}
    return {
        "embed": _quantized_spec(specs["embed"]),
        "layers": layers,
        "norm_f": specs["norm_f"],
        "head": _quantized_spec(specs["head"]),
    }
