"""Tracing/profiling hooks (SURVEY §5: absent in the reference; optional
here).

Thin wrappers over the JAX profiler so traces can be captured on any task
and inspected with Perfetto/TensorBoard.  Enable globally by exporting
``TPUMESOS_TRACE_DIR`` — the trainer and node runtime leave these off by
default (profiling is opt-in; it perturbs step timing).
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Iterator, Optional

TRACE_DIR_ENV = "TPUMESOS_TRACE_DIR"


@contextmanager
def trace(logdir: Optional[str] = None) -> Iterator[Optional[str]]:
    """Capture a profiler trace for the enclosed block.

    Yields the trace directory, or None (block still runs, untraced) when no
    directory is configured — so call sites can wrap unconditionally.
    """
    logdir = logdir or os.environ.get(TRACE_DIR_ENV)
    if not logdir:
        yield None
        return
    import jax
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (shows up on the Perfetto timeline)."""
    import jax
    return jax.profiler.TraceAnnotation(name)
