"""Structured logging (reference: tfmesos/utils.py:18-27, console-only INFO).

We keep the same one-call setup surface but emit a structured, parseable
format and allow a level override via ``TPUMESOS_LOGLEVEL``.
"""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"


def setup_logger(logger: logging.Logger, quiet: bool = False) -> None:
    if quiet:
        return
    if any(isinstance(h, logging.StreamHandler) for h in logger.handlers):
        return
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(handler)
    # We attach our own handler, so don't ALSO bubble up to the root
    # handler third-party libs (absl/orbax) install — each record would
    # print twice.
    logger.propagate = False
    level = os.environ.get("TPUMESOS_LOGLEVEL", "INFO").upper()
    logger.setLevel(getattr(logging, level, logging.INFO))


def get_logger(name: str, quiet: bool = False) -> logging.Logger:
    logger = logging.getLogger(name)
    setup_logger(logger, quiet=quiet)
    return logger
