"""Authoritative JAX platform selection.

A site-installed PJRT plugin (observed with the axon TPU relay) can pin the
platform through ``jax.config`` at interpreter start, which *beats* the
``JAX_PLATFORMS`` env var.  Every place that needs a specific platform —
tests (virtual CPU mesh), the driver's multi-chip dry run, and the node
runtime honoring the env it was launched with — must therefore set the
config explicitly before the first backend initialization.  This module is
the single implementation of that workaround.
"""

from __future__ import annotations

import os
import re
from typing import Optional

_COUNT_RE = r"--xla_force_host_platform_device_count=(\d+)"


def force_platform(platform: Optional[str] = None,
                   min_host_devices: Optional[int] = None) -> None:
    """Make platform selection authoritative over any site plugin pinning.

    ``platform=None`` honors ``JAX_PLATFORMS`` from the environment (the node
    runtime's contract); a string forces that platform and exports the env
    var so child processes inherit it.  ``min_host_devices`` raises the
    virtual host-device count in ``XLA_FLAGS`` if it is absent or smaller.

    Only effective before the first backend init; callers that must be sure
    should verify ``jax.devices()`` afterwards (``__graft_entry__`` re-execs
    into a clean interpreter when the check fails).
    """
    if min_host_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(_COUNT_RE, flags)
        if not m or int(m.group(1)) < min_host_devices:
            flags = re.sub(r"\s*" + _COUNT_RE, "", flags)
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={min_host_devices}"
            ).strip()
    if platform is not None:
        os.environ["JAX_PLATFORMS"] = platform
    else:
        platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax
        try:
            jax.config.update("jax_platforms", platform)
        except Exception:
            pass  # backend already initialized; callers verify devices
