from tfmesos_tpu.utils.logging import setup_logger, get_logger

__all__ = ["setup_logger", "get_logger"]
