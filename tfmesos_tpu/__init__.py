"""tfmesos_tpu — a TPU-native cluster framework with the capabilities of
douban/tfmesos.

A lightweight control plane that allocates resources (from a Mesos cluster or
the local host), boots a ``jax.distributed`` runtime on them, and hands user
code a GSPMD mesh — the TPU-era successor of the reference's ps/worker
``tf.train.Server`` ClusterSpec (see SURVEY.md).

Public surface mirrors the reference (tfmesos/__init__.py:7-22): the
``cluster()`` context manager with identical jobs-normalization semantics.
"""

from __future__ import annotations

from contextlib import contextmanager

from tfmesos_tpu.spec import Job, normalize_jobs
from tfmesos_tpu.scheduler import ClusterError, RemoteError, TPUMesosScheduler

__VERSION__ = "0.4.0"

__all__ = ["cluster", "Job", "TPUMesosScheduler", "ClusterError",
           "RemoteError", "__VERSION__"]


@contextmanager
def cluster(jobs, **kwargs):
    """Bring up a cluster, yield the scheduler handle, always tear down.

    ``jobs`` may be a Job, a dict of Job kwargs, or a list of either —
    the reference's normalization contract (tfmesos/__init__.py:9-16).
    Keyword arguments pass through to :class:`TPUMesosScheduler`.
    """
    jobs = normalize_jobs(jobs)
    scheduler = TPUMesosScheduler(jobs, **kwargs)
    scheduler.start()
    try:
        yield scheduler
    finally:
        scheduler.stop()
