"""TPU cluster scheduler: offer matching, rendezvous, config broadcast.

This is the analogue of the reference's ``TFMesosScheduler``
(scheduler.py:180-481), re-designed rather than ported:

* Resource acquisition goes through a pluggable :class:`ResourceBackend`
  (Mesos v1 HTTP or local subprocesses) instead of hard-wiring pymesos.
* The rendezvous loop is event-driven (``selectors``) instead of the
  reference's 0.1s select poll (scheduler.py:322-323, 341-361).
* The broadcast config carries everything a ``jax.distributed`` process needs
  (rank, world size, coordinator address) in addition to the reference's
  ``cluster_def`` map (scheduler.py:296-308), so between-graph PS replication
  becomes a GSPMD mesh over ICI all-reduce.
* The two-phase failure policy is preserved exactly: revive-with-new-uuid up
  to ``MAX_FAILURE_COUNT`` before the cluster starts (scheduler.py:404-434),
  fail-fast after (scheduler.py:394-401) — the right policy for a TPU mesh,
  which cannot hot-swap members mid-program.
* ``restart_policy="elastic"`` upgrades the post-start half: instead of
  aborting the job on a task death or agent loss, the scheduler tears down
  the survivors, bumps a cluster **generation** id, re-forms the whole gang
  from fresh offers (exponential backoff + jitter, a sliding-window restart
  budget before going fatal after all) and re-broadcasts ``cluster_def``.
  A TPU mesh still cannot hot-swap members mid-program — elasticity here is
  whole-gang replacement, the TF-Replicator/production-trainer baseline of
  "workers restart and resume from checkpoint", not pretend PS elasticity.
  The generation id is fenced through the wire protocol: registrations and
  Mode-A replies carry it, and stale-generation messages from zombie tasks
  of a previous gang are logged and dropped, never matched to current state
  (see docs/FAULT_TOLERANCE.md).
* ``gang_scheduling=True`` additionally makes placement all-or-nothing across
  an offer batch, matching TPU slice atomicity (a slice's topology fixes the
  process count; partial bring-up is useless).
"""

from __future__ import annotations

import collections
import getpass
import os
import random
import selectors
import socket
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.backends import FOREVER, ResourceBackend, first_fit
from tfmesos_tpu.spec import Job, Offer, Task, TaskStatus
from tfmesos_tpu.utils.logging import get_logger

MAX_FAILURE_COUNT = 3  # reference: scheduler.py:181


class ClusterError(RuntimeError):
    """Fatal cluster failure (reference raises bare RuntimeError,
    scheduler.py:394-401, 416-420, 445-457)."""


class RemoteError(ClusterError):
    """A dispatched function raised on a task — user-code failure, not
    infrastructure death.  Restart supervision must NOT retry these."""


class TPUMesosScheduler:
    """Owns the task table and drives bring-up → run → teardown.

    Constructor surface mirrors the reference's option set
    (scheduler.py:183-221) with TPU-era renames: ``gpus``→``chips``,
    ``protocol`` defaults to ``'xla'``.
    """

    def __init__(self, task_spec: List[Job], backend: Optional[ResourceBackend] = None,
                 master: Optional[str] = None, name: Optional[str] = None,
                 quiet: bool = False, volumes: Optional[Dict[str, str]] = None,
                 containerizer_type: Optional[str] = None,
                 force_pull_image: bool = False,
                 forward_addresses: Optional[Dict[str, str]] = None,
                 protocol: str = "xla", env: Optional[Dict[str, str]] = None,
                 extra_config: Optional[Dict[str, Any]] = None,
                 role: str = "*", mesh_axes: Optional[Dict[str, int]] = None,
                 gang_scheduling: bool = False,
                 start_timeout: float = 300.0,
                 token_transport: Optional[str] = None,
                 token: Optional[str] = None,
                 restart_policy: str = "fail_fast",
                 max_cluster_restarts: int = 3,
                 restart_window: float = 600.0,
                 restart_backoff: float = 1.0,
                 restart_backoff_max: float = 30.0,
                 restart_jitter: float = 0.1,
                 restart_seed: Optional[int] = None,
                 dynamic: bool = False,
                 chaos=None):
        self.task_spec = task_spec
        self.master = master or os.environ.get("MESOS_MASTER")
        # Default framework name mirrors scheduler.py:189-190.
        self.name = name or f"[tpumesos] {getpass.getuser()} {' '.join(sys.argv)}"
        self.quiet = quiet
        self.volumes = volumes or {}
        self.containerizer_type = containerizer_type
        self.force_pull_image = force_pull_image
        self.forward_addresses = forward_addresses or {}
        self.protocol = protocol
        self.extra_config = extra_config or {}
        self.role = role
        self.mesh_axes = mesh_axes
        self.gang_scheduling = gang_scheduling
        self.start_timeout = start_timeout
        self.env = dict(env or {})
        if restart_policy not in ("fail_fast", "elastic"):
            raise ValueError(f"restart_policy must be fail_fast|elastic, "
                             f"got {restart_policy!r}")
        # Dynamic mode (the serving fleet): the task table is a runtime
        # property — add_task()/remove_task() grow and shrink it after
        # start(), registrations are served continuously instead of
        # through one gang barrier, and a task death is a SERVING event
        # (the control loop re-converges), never a cluster-fatal one.
        # Elastic recovery is whole-gang replacement and has no meaning
        # over a membership that changes one task at a time.
        self.dynamic = bool(dynamic)
        if self.dynamic and restart_policy == "elastic":
            raise ValueError("dynamic task management and elastic gang "
                             "recovery are mutually exclusive: a dynamic "
                             "fleet has no gang to re-form")
        self.restart_policy = restart_policy
        self.max_cluster_restarts = int(max_cluster_restarts)
        self.restart_window = float(restart_window)
        self.restart_backoff = float(restart_backoff)
        self.restart_backoff_max = float(restart_backoff_max)
        self.restart_jitter = float(restart_jitter)
        # Seedable jitter so fault-injection tests replay exactly.
        self._restart_rng = random.Random(restart_seed)
        self.chaos = chaos

        self.log = get_logger("tfmesos_tpu.scheduler", quiet=quiet)
        # One token per bring-up by default; an explicit ``token`` lets
        # co-resident control-plane services (the fleet's registry and
        # gateway) share a single cluster secret with the tasks.
        self.token = token or wire.new_token()

        # Expand Jobs into the task table (reference: scheduler.py:201-217).
        # Creation order — jobs in declared order, indices ascending — IS the
        # global rank order, the deterministic-rank precedent of the sorted
        # cluster_def at scheduler.py:291-293.
        self.tasks: List[Task] = []
        for job in task_spec:
            for task_index in range(job.start, job.num):
                self.tasks.append(Task(job.name, task_index, cpus=job.cpus,
                                       mem=job.mem, chips=job.chips,
                                       cmd=job.cmd, volumes=self.volumes))

        if backend is None:
            backend = self._default_backend()
        self.backend = backend

        # How tasks learn the HMAC token.  A plain env var is readable via
        # Mesos state endpoints and /proc environ (advisor finding), so
        # co-located backends default to a mode-0600 file; "secret" renders a
        # Mesos SECRET-typed variable for clusters with a secret resolver.
        colocated = getattr(backend, "colocated", False)
        if token_transport is None:
            token_transport = "file" if colocated else "env"
        if token_transport not in ("env", "file", "secret"):
            raise ValueError(f"token_transport must be env|file|secret, "
                             f"got {token_transport!r}")
        if token_transport == "file" and not colocated:
            raise ValueError(
                "token_transport='file' needs a colocated backend: a remote "
                "task cannot read the scheduler's local token file")
        if token_transport == "secret" and colocated:
            raise ValueError(
                "token_transport='secret' is a Mesos secret-resolver "
                "feature; colocated backends use 'file' (the default)")
        self.token_transport = token_transport
        self._token_file: Optional[str] = None

        if not self.tasks and not self.dynamic:
            raise ValueError("job spec expands to zero tasks")
        # Per-job index counters and bring-up failure counts for tasks
        # added at runtime (dynamic mode).
        self._dyn_index: Dict[str, int] = {}
        for task in self.tasks:
            self._dyn_index[task.job_name] = max(
                self._dyn_index.get(task.job_name, 0), task.task_index + 1)
        self.dynamic_failures: Dict[str, int] = {}
        # Dynamic-death notification (the fleet's gang manager): called
        # with the dead Task AFTER it left the table, on a fresh thread —
        # the callback tears down siblings via remove_task/backend.kill,
        # which must never run on the status-processing thread.
        self.on_dynamic_death = None
        self._gang_seq = 0

        self._lock = threading.RLock()
        self.started = False
        self._registered_once = False
        self._broadcasting = False
        self._stopped = False
        self._fatal: Optional[str] = None
        # Heartbeat-revive gating: the backstop only fires on EVIDENCE the
        # offer tap is closed (a revive POST failed, or no offer arrived
        # since the last heartbeat) — an unconditional ~15s revive would
        # clear every decline filter and churn re-offers on a busy master
        # while gang scheduling's short declines are deliberate.
        self._revive_failed = False
        self._offers_since_beat = False
        self.task_failure_count: Dict[str, int] = {}
        self.job_finished: Dict[str, int] = {}
        self._listen: Optional[socket.socket] = None
        self.addr: Optional[str] = None
        self._call_id = 0

        # Elastic recovery state.  ``generation`` is the gang epoch: it is
        # stamped into every launch's env, echoed in registrations and
        # Mode-A replies, and bumped the moment a recovery is accepted —
        # the fencing token that keeps zombies of a dead gang from being
        # mistaken for members of the current one.
        self.generation = 0
        self.cluster_restarts = 0           # successful re-formations
        self._recovering = False
        self._recover_teardown_done = False
        self._recover_reason: Optional[str] = None
        self._recover_event = threading.Event()
        self._restart_times: collections.deque = collections.deque()
        self._backoff_exponent = 0
        self._elastic_thread: Optional[threading.Thread] = None
        self._dynamic_thread: Optional[threading.Thread] = None

    # -- backend selection -------------------------------------------------

    def _default_backend(self) -> ResourceBackend:
        if self.master in (None, "", "local"):
            from tfmesos_tpu.backends.local import LocalBackend
            return LocalBackend()
        try:
            from tfmesos_tpu.backends.mesos import MesosBackend
        except ImportError as e:
            raise ClusterError(f"Mesos backend unavailable: {e}") from e
        return MesosBackend(self.master, framework_name=self.name, role=self.role)

    # -- backend callback surface -----------------------------------------

    def on_registered(self, info: Dict[str, Any]) -> None:
        self.log.info("backend registered: %s", info)
        with self._lock:
            rejoin = self._registered_once
            self._registered_once = True
            unplaced = any(not t.offered for t in self.tasks)
        if rejoin and unplaced:
            # Re-subscription after a stream break: a REVIVE issued while
            # the master was unreachable may have been lost, and FOREVER
            # decline filters survive failover — re-open the offer tap.
            self._revive_backend("re-registration")
        version = info.get("master_version")
        if self.containerizer_type is None and version:
            # Reference semantics (scheduler.py:378-382): Mesos >= 1.0 uses
            # the unified MESOS containerizer, older masters need DOCKER.
            try:
                major = int(str(version).split(".")[0])
            except ValueError:
                return
            self.containerizer_type = "MESOS" if major >= 1 else "DOCKER"
            self.log.info("auto-detected containerizer %s (master %s)",
                          self.containerizer_type, version)

    def on_offers(self, offers: List[Offer]) -> None:
        """Offer matching (reference resourceOffers, scheduler.py:223-277).

        State decisions and TaskInfo rendering happen under ``_lock``;
        the backend calls they produce (HTTP POSTs on Mesos, up to 30s
        each) run OUTSIDE it, so a slow master never stalls ``on_status``
        processing on the subscribe thread.
        """
        to_decline: List[tuple] = []        # (offer, refuse_seconds)
        to_launch: List[tuple] = []         # (offer, infos, placed, ids)
        suppress = False
        with self._lock:
            self._offers_since_beat = True
            if self._fatal or self._stopped:
                to_decline = [(o, 5.0) for o in offers]
            elif all(task.offered for task in self.tasks):
                suppress = True
                to_decline = [(o, FOREVER) for o in offers]
            elif self.gang_scheduling and not self._gang_fits(offers):
                # TPU slice atomicity: refuse partial placement; short
                # refusal so re-offers accumulate into a big enough batch.
                to_decline = [(o, 1.0) for o in offers]
            else:
                batch_tasks = self._batch_order(offers)
                for offer in offers:
                    placed = first_fit(batch_tasks, offer)
                    if not placed:
                        to_decline.append((offer, 5.0))
                        continue
                    infos = [t.to_task_info(offer, self.addr, self.token,
                                            containerizer_type=self.containerizer_type,
                                            force_pull_image=self.force_pull_image,
                                            env=self._launch_env(t),
                                            token_file=self._token_file,
                                            secret_token=(self.token_transport
                                                          == "secret"))
                             for t in placed]
                    to_launch.append((offer, infos, placed,
                                      [t.id for t in placed]))
        if suppress:
            self.backend.suppress()
        for offer, refuse_seconds in to_decline:
            self.backend.decline(offer, refuse_seconds=refuse_seconds)
        for offer, infos, placed, ids in to_launch:
            with self._lock:
                # A terminal status on another thread (LocalBackend's
                # reaper) can reset() a placed task between rendering and
                # this launch; launching the stale batch would spawn
                # processes under ids the scheduler no longer tracks.
                stale = [t for t, tid in zip(placed, ids) if t.id != tid]
                if stale:
                    for t, tid in zip(placed, ids):
                        if t.id == tid and t.offered:
                            # Un-place the still-valid batchmates (nothing
                            # launched); the next offer re-places them.
                            t.offered = False
                            t.offer_id = t.agent_id = t.hostname = None
            if stale:
                self.log.warning(
                    "dropping launch on %s: %d task(s) reset between "
                    "placement and launch", offer.hostname, len(stale))
                self.backend.decline(offer, refuse_seconds=1.0)
                continue
            self.log.info("launching %d task(s) on %s: %s",
                          len(placed), offer.hostname, placed)
            self.backend.launch(offer, infos)
            with self._lock:
                # The reset can also race the launch call itself (the
                # pre-check only narrows the window): a task reset DURING
                # backend.launch leaves a process running under an id the
                # scheduler no longer tracks — terminal statuses for
                # unknown ids are ignored, so it would leak.  Kill it.
                dead = [tid for t, tid in zip(placed, ids) if t.id != tid]
            for tid in dead:
                self.log.warning("task %s reset during launch; killing the "
                                 "stale process", tid[:8])
                try:
                    self.backend.kill(tid)
                except Exception as e:
                    self.log.warning("stale-launch kill of %s failed: %s",
                                     tid[:8], e)

    def _batch_order(self, offers: List[Offer]) -> List:
        """Gang-atomic placement order for one offer batch (lock held).

        Dynamic tasks added via :meth:`add_gang` carry a ``gang`` label;
        a gang is placed ALL-OR-NOTHING within a batch: a reservation
        pass checks each gang's unplaced members against the batch's
        free capacity (in the same greedy order the real ``first_fit``
        loop will use), admits gangs that wholly fit, and withholds the
        rest for a later, bigger batch — a gang may legitimately split
        ACROSS offers (hosts) within the batch, never across batches.
        Admitted gang members sort first so loose tasks cannot eat the
        capacity the reservation just verified."""
        loose = [t for t in self.tasks
                 if getattr(t, "gang", None) is None]
        gangs: Dict[str, List] = {}
        for t in self.tasks:
            g = getattr(t, "gang", None)
            if g is not None and not t.offered:
                gangs.setdefault(g, []).append(t)
        if not gangs:
            return loose
        free = [[o.cpus, o.mem, o.chips] for o in offers]
        admitted: List = []
        for gid, members in gangs.items():
            trial = [slot[:] for slot in free]
            for t in members:
                for slot in trial:
                    if (slot[0] >= t.cpus and slot[1] >= t.mem
                            and slot[2] >= t.chips):
                        slot[0] -= t.cpus
                        slot[1] -= t.mem
                        slot[2] -= t.chips
                        break
                else:
                    self.log.info(
                        "withholding gang %s from this offer batch: "
                        "%d member(s) do not all fit", gid, len(members))
                    break
            else:
                free = trial
                admitted.extend(members)
        return admitted + loose

    def _gang_fits(self, offers: List[Offer]) -> bool:
        """Would the *entire* remaining task set fit across this offer batch?"""
        free = [[o.cpus, o.mem, o.chips] for o in offers]
        for task in self.tasks:
            if task.offered:
                continue
            for slot in free:
                if slot[0] >= task.cpus and slot[1] >= task.mem and slot[2] >= task.chips:
                    slot[0] -= task.cpus
                    slot[1] -= task.mem
                    slot[2] -= task.chips
                    break
            else:
                return False
        return True

    def on_status(self, status: TaskStatus) -> None:
        """Two-phase failure policy (reference statusUpdate,
        scheduler.py:384-420)."""
        # The ack and revive are HTTP POSTs on Mesos — keep them outside
        # the lock (a slow master must not stall other status processing).
        self.backend.acknowledge(status)
        revive = False
        with self._lock:
            task = self._find_task(status.task_id)
            if task is None:
                if status.terminal and status.state != "TASK_FINISHED":
                    # Update for a stale (revived) task id — ignore, as the
                    # reference does for unknown ids.
                    self.log.info("status for unknown task %s: %s",
                                  status.task_id, status.state)
                return
            task.last_state = status.state
            if not status.terminal:
                return
            if getattr(task, "dynamic", False):
                # A dynamic (serving) task's death is a SERVING event:
                # drop it from the table — the fleet routes around it and
                # the autoscaler re-converges the tier — never a
                # cluster-fatal or a revive charge.  (Tasks removed via
                # remove_task() report under an id no longer in the
                # table and land in the unknown-id branch above.)
                self.tasks.remove(task)
                if status.state == "TASK_FINISHED":
                    self.log.info("dynamic task finished: %s", task)
                else:
                    self.dynamic_failures[task.job_name] = \
                        self.dynamic_failures.get(task.job_name, 0) + 1
                    self.log.warning("dynamic task %s terminated: %s %s",
                                     task, status.state, status.message)
                    cb = self.on_dynamic_death
                    if cb is not None:
                        # Off-thread: the callback (gang teardown) kills
                        # sibling tasks — backend HTTP it must not run
                        # on the status thread or under our lock.
                        threading.Thread(
                            target=self._fire_dynamic_death,
                            args=(cb, task), daemon=True,
                            name="tpumesos-dyn-death").start()
                return
            if status.state == "TASK_FINISHED":
                self.job_finished[task.job_name] = \
                    self.job_finished.get(task.job_name, 0) + 1
                self.log.info("task finished: %s (%d done in job %s)",
                              task, self.job_finished[task.job_name], task.job_name)
                return
            elif self.started or self._broadcasting:
                # Post-start (or mid-broadcast, when peers may already be
                # acting on their config): fail fast, whole-cluster abort
                # (reference: scheduler.py:394-401) — unless the elastic
                # policy turns this into a gang re-formation.
                self._post_start_failure(
                    f"task {task} terminated after cluster start: "
                    f"{status.state} {status.message}")
            elif self._recovering and not self._recover_teardown_done:
                # Recovery accepted but the old gang not yet torn down:
                # these are the expected deaths of that gang (one host
                # loss reports once per task).  The pre-start revive path
                # must NOT run here — it would relaunch tasks with zero
                # backoff (for teardown to immediately kill) and charge
                # the bring-up failure budget for deaths that already
                # bought the recovery.  After teardown, old-gang statuses
                # carry unknown (reset) ids and are ignored above; new-
                # gang bring-up failures take the normal revive path.
                self.log.info("ignoring terminal status for %s during "
                              "gang teardown: %s", task, status.state)
            else:
                # Pre-start: revive with a fresh uuid up to MAX_FAILURE_COUNT
                # (reference: scheduler.py:404-434).
                key = f"{task.job_name}:{task.task_index}"
                self.task_failure_count[key] = \
                    self.task_failure_count.get(key, 0) + 1
                if self.task_failure_count[key] >= MAX_FAILURE_COUNT:
                    self._set_fatal(
                        f"task {task} failed {MAX_FAILURE_COUNT} times "
                        f"during bring-up: {status.state} {status.message}")
                else:
                    self.log.warning("reviving task %s after %s (%s), "
                                     "attempt %d", task, status.state,
                                     status.message,
                                     self.task_failure_count[key] + 1)
                    task.reset()
                    revive = True
        if revive:
            # Task state is already reset; a failed REVIVE POST (master
            # unreachable) must not unwind the event thread — the
            # heartbeat backstop and the re-registration hook re-issue it
            # (_revive_backend tracks the failure for them).
            self._revive_backend("post-status")

    def on_rescind(self, offer_id: str) -> None:
        """An outstanding offer was withdrawn by the master.  Tasks placed
        on it whose launch never confirmed (no TASK_RUNNING seen) are
        RE-QUEUED for placement — without this they would sit
        offered=True until ``start_timeout``.  Rescinds are ordinary
        offer churn on a busy master, not task failures: they do NOT
        consume the two-phase failure budget (three rescinds of one
        slot's placements must not abort a cluster where nothing ever
        crashed).  The reference ignored rescinds entirely (no
        offerRescinded handler); a stale-offer launch then hung its
        bring-up."""
        to_requeue: List[str] = []
        revive = False
        with self._lock:
            for task in self.tasks:
                if (task.offer_id == offer_id and task.offered
                        and not task.initialized
                        and task.last_state != "TASK_RUNNING"):
                    to_requeue.append(task.id)
                    self.log.warning(
                        "offer %s rescinded before launch of %s confirmed; "
                        "re-queuing placement", offer_id, task)
                    task.reset()
                    revive = True
        for tid in to_requeue:
            # The ACCEPT may have raced the rescind server-side; a KILL for
            # a task that never launched is a no-op, and one that did
            # launch must die anyway (its id is now stale).  Guarded: one
            # failed HTTP call must not strand the remaining tasks.
            try:
                self.backend.kill(tid)
            except Exception as e:
                self.log.warning("rescind kill of %s failed: %s", tid[:8], e)
        if revive:
            self._revive_backend("rescind")

    def _revive_backend(self, context: str) -> None:
        """One revive POST with failure tracking: a failed POST arms the
        heartbeat backstop (``on_heartbeat``) to retry."""
        try:
            self.backend.revive()
            with self._lock:
                self._revive_failed = False
        except Exception as e:
            with self._lock:
                self._revive_failed = True
            self.log.warning("%s revive failed: %s", context, e)

    def on_heartbeat(self) -> None:
        """Master heartbeat (~15s): the liveness backstop for a REVIVE
        that failed or was rejected while the subscribe stream stayed
        healthy — with FOREVER decline filters active after suppression,
        nothing else would ever re-open the offer tap for an unplaced
        task (bring-up would idle into start_timeout).

        Gated on EVIDENCE the tap is closed: a prior revive POST failed,
        or no offer arrived since the last heartbeat.  While offers are
        flowing normally (gang scheduling's short declines included) an
        unconditional revive would clear every decline filter ~15s and
        spam re-offers on a busy master."""
        with self._lock:
            need = (not self._stopped and self._fatal is None
                    and (not self.started or self.dynamic)
                    and any(not t.offered for t in self.tasks)
                    and (self._revive_failed
                         or not self._offers_since_beat))
            self._offers_since_beat = False
        if need:
            self._revive_backend("heartbeat")

    def on_agent_lost(self, agent_id: str) -> None:
        """Reference slaveLost/executorLost (scheduler.py:445-453); under
        the elastic policy a lost agent triggers gang re-formation."""
        with self._lock:
            if self.started:
                self._post_start_failure(f"agent lost: {agent_id}")
                return
            lost = [task.id for task in self.tasks
                    if task.agent_id == agent_id and not task.initialized]
        for tid in lost:
            self.on_status(TaskStatus(tid, "TASK_LOST",
                                      message="agent lost",
                                      agent_id=agent_id))

    def on_error(self, message: str) -> None:
        self._set_fatal(f"backend error: {message}")

    def _set_fatal(self, message: str) -> None:
        if self._fatal is None:
            self._fatal = message
            self.log.error("fatal: %s", message)
            # Unblock the elastic thread so it can observe the fatal and
            # exit instead of waiting for a recovery that will never come.
            self._recover_event.set()

    # -- elastic recovery --------------------------------------------------

    def _launch_env(self, task=None) -> Dict[str, str]:
        """Per-launch env: the user's plus the generation, so a task
        knows which gang epoch launched it (it echoes the value in its
        registration and every Mode-A reply — the fencing token).
        Dynamic tasks carry THEIR OWN launch generation (stamped at
        add_task time): a blue-green rollout bumps the cluster
        generation while old-generation fallback replicas are still
        legitimately being (re)offered, and those must not silently
        inherit the new epoch."""
        env = dict(self.env)
        gen = getattr(task, "generation", None) if task is not None else None
        env["TPUMESOS_GENERATION"] = str(
            self.generation if gen is None else gen)
        extra = getattr(task, "extra_env", None) if task is not None else None
        if extra:
            env.update(extra)
        return env

    def _post_start_failure(self, why: str) -> None:
        """A task/agent died after cluster start (lock held): fatal under
        fail_fast (the reference policy), a recovery request under
        elastic."""
        if self.restart_policy != "elastic":
            self._set_fatal(why)
        else:
            self._request_recovery(why)

    def _charge_restart(self, why: str) -> bool:
        """Spend one unit of the sliding-window restart budget (lock
        held).  False — and the cluster is fatal — when the window already
        holds ``max_cluster_restarts`` restarts: a crash loop faster than
        the window is a real problem restarts cannot fix."""
        now = time.monotonic()
        while (self._restart_times
               and now - self._restart_times[0] > self.restart_window):
            self._restart_times.popleft()
        if len(self._restart_times) >= self.max_cluster_restarts:
            self._set_fatal(
                f"elastic restart budget exhausted "
                f"({self.max_cluster_restarts} restarts within "
                f"{self.restart_window:.0f}s): {why}")
            return False
        self._restart_times.append(now)
        self._backoff_exponent = len(self._restart_times) - 1
        return True

    def _request_recovery(self, why: str) -> None:
        """Accept (at most once per incident) a post-start failure as a
        recovery trigger: charge the budget, bump the generation, flip the
        cluster un-started, and wake the recovery thread.  Idempotent
        while a recovery is in flight — one host loss surfaces as many
        signals (dispatch EOF, TASK_FAILED per task, agent-lost) and must
        buy exactly one re-formation.  Lock held."""
        if self._fatal or self._stopped or self._recovering:
            return
        if not self._charge_restart(why):
            return
        self._recovering = True
        self._recover_teardown_done = False
        self._recover_reason = why
        self.started = False
        self._broadcasting = False
        self.generation += 1
        self.log.warning("elastic recovery -> generation %d: %s",
                         self.generation, why)
        self._recover_event.set()

    def _elastic_loop(self) -> None:
        """The recovery thread: parked on ``_recover_event``, runs one
        gang re-formation per accepted recovery request."""
        while True:
            self._recover_event.wait()
            with self._lock:
                if self._stopped or self._fatal is not None:
                    return
                self._recover_event.clear()
                if not self._recovering:
                    continue
            try:
                self._recover()
            except Exception as e:      # pragma: no cover - defensive
                with self._lock:
                    self._set_fatal(f"elastic recovery crashed: {e}")
                return

    def _recover(self) -> None:
        """Tear down the old gang and form a new one, retrying (each retry
        re-charged against the restart budget) until the gang is up, the
        budget is gone, or the scheduler stops."""
        while True:
            with self._lock:
                if self._stopped or self._fatal is not None:
                    return
                backoff = min(
                    self.restart_backoff * (2 ** self._backoff_exponent),
                    self.restart_backoff_max)
                backoff *= 1.0 + self.restart_jitter * self._restart_rng.random()
                generation = self.generation
            self.log.warning(
                "elastic: tearing down generation %d survivors; re-forming "
                "gang in %.2fs (restart %d)", generation - 1, backoff,
                len(self._restart_times))
            self._teardown_tasks()
            with self._lock:
                self._recover_teardown_done = True
            if self._interruptible_sleep(backoff):
                return
            with self._lock:
                if self._stopped or self._fatal is not None:
                    return
                # Fresh bring-up budgets for the new gang: the pre-start
                # revive counter guards ONE bring-up; crash loops across
                # generations are bounded by the cluster restart window.
                self.task_failure_count.clear()
                self.job_finished.clear()
            self._revive_backend("elastic recovery")
            try:
                self._form_gang()
            except ClusterError as e:
                with self._lock:
                    if self._stopped or self._fatal is not None:
                        return
                    if not self._charge_restart(f"gang re-formation failed: {e}"):
                        return
                self.log.warning("elastic: re-formation failed (%s); "
                                 "retrying", e)
                continue
            with self._lock:
                # _recovering was already cleared atomically with
                # started=True inside _start_cluster.
                self.cluster_restarts += 1
            self.log.warning("elastic: gang re-formed — generation %d live "
                             "(%d cluster restart(s) so far)",
                             generation, self.cluster_restarts)
            return

    def _teardown_tasks(self) -> None:
        """Reset every task to a fresh identity and kill whatever of the
        old gang still runs.  Survivors of a partial failure cannot be
        kept: the mesh program they were running is gone, and their old
        connections/ids must never be confused with the new gang's."""
        with self._lock:
            old_ids = [t.id for t in self.tasks]
            for task in self.tasks:
                task.reset()        # closes the connection, fresh uuid
        for tid in old_ids:
            try:
                self.backend.kill(tid)
            except Exception as e:
                self.log.warning("teardown kill of %s failed: %s", tid[:8], e)

    def _interruptible_sleep(self, seconds: float) -> bool:
        """Sleep in short slices; True when stop/fatal interrupted it."""
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            with self._lock:
                if self._stopped or self._fatal is not None:
                    return True
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
        return False

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Block until the cluster is started and not mid-recovery.  True
        when ready; False on timeout; raises :class:`ClusterError` when
        the cluster went fatal (budget exhausted, bring-up dead).  The
        driver-side pairing for elastic mode: catch the
        :class:`ClusterError` a dispatch raised, ``wait_ready()``, restore
        your checkpoint, and continue."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                if self._fatal:
                    raise ClusterError(self._fatal)
                if self._stopped:
                    raise ClusterError("scheduler stopped")
                if self.started and not self._recovering:
                    return True
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.05)

    @property
    def restart_stats(self) -> Dict[str, Any]:
        """Observability counters for the elastic policy."""
        with self._lock:
            # Expire window-aged restarts so budget_left reflects what
            # _charge_restart would actually allow right now.
            now = time.monotonic()
            while (self._restart_times
                   and now - self._restart_times[0] > self.restart_window):
                self._restart_times.popleft()
            return {
                "generation": self.generation,
                "cluster_restarts": self.cluster_restarts,
                "recovering": self._recovering,
                "restart_budget_left": max(
                    0, self.max_cluster_restarts - len(self._restart_times)),
            }

    def _fire_dynamic_death(self, cb, task) -> None:
        try:
            cb(task)
        except Exception as e:
            self.log.warning("on_dynamic_death(%s) raised: %s", task, e)

    def _find_task(self, task_id: str) -> Optional[Task]:
        for task in self.tasks:
            if task.id == task_id:
                return task
        return None

    # -- dynamic task management (serving fleets) --------------------------

    def add_task(self, job_name: str, cmd: str, cpus: float = 1.0,
                 mem: float = 1024.0, chips: int = 0,
                 env: Optional[Dict[str, str]] = None) -> Task:
        """Launch ONE new Mode-B task at runtime (dynamic mode only):
        the task enters the table with the NEXT index for its job, the
        offer tap re-opens, and its registration is served by the
        dynamic rendezvous.  The cluster generation current NOW is
        stamped on the task — a later rollout bump must not re-brand a
        launch that predates it.  ``env`` rides the launch env on top
        of the scheduler-wide one (gang identity travels this way)."""
        if not self.dynamic:
            raise ClusterError("add_task requires dynamic=True")
        with self._lock:
            task = self._add_task_locked(job_name, cmd, cpus, mem,
                                         chips, env)
        self.log.info("dynamic task added: %s (generation %d)", task,
                      task.generation)
        self._revive_backend("add_task")
        return task

    def _add_task_locked(self, job_name, cmd, cpus, mem, chips,
                         env) -> Task:
        if self._stopped:
            raise ClusterError("scheduler stopped")
        if self._fatal:
            raise ClusterError(self._fatal)
        index = self._dyn_index.get(job_name, 0)
        self._dyn_index[job_name] = index + 1
        task = Task(job_name, index, cpus=cpus, mem=mem,
                    chips=chips, cmd=cmd, volumes=self.volumes)
        task.dynamic = True
        task.generation = self.generation
        if env:
            task.extra_env = dict(env)
        self.tasks.append(task)
        return task

    def add_gang(self, job_name: str, cmds: List[str], cpus: float = 1.0,
                 mem: float = 1024.0, chips: int = 0,
                 envs: Optional[List[Dict[str, str]]] = None) -> List[Task]:
        """Launch N tasks as ONE atomic gang (dynamic mode only): all
        members enter the table under a single lock hold — one launch
        generation, one gang label — and the offer loop places the
        gang all-or-nothing within an offer batch (it may span hosts,
        never epochs).  Returns the member tasks in rank order; the
        per-member ``envs`` dicts carry rank/size/coordination env."""
        if not self.dynamic:
            raise ClusterError("add_gang requires dynamic=True")
        if not cmds:
            raise ValueError("add_gang needs at least one member cmd")
        if envs is not None and len(envs) != len(cmds):
            raise ValueError("envs must match cmds one-to-one")
        with self._lock:
            self._gang_seq += 1
            gang_id = f"{job_name}/g{self._gang_seq}"
            members = []
            for rank, cmd in enumerate(cmds):
                env = dict(envs[rank]) if envs else {}
                # The gang contract rides the launch env: every member
                # learns its identity from these three variables (the
                # caller cannot stamp them — the gang id is minted
                # under this very lock hold).
                env["TPUMESOS_GANG_ID"] = gang_id
                env["TPUMESOS_GANG_SIZE"] = str(len(cmds))
                env["TPUMESOS_GANG_RANK"] = str(rank)
                task = self._add_task_locked(
                    job_name, cmd, cpus, mem, chips, env)
                task.gang = gang_id
                members.append(task)
            gen = members[0].generation
        self.log.info("dynamic gang added: %s x%d (generation %d)",
                      gang_id, len(members), gen)
        self._revive_backend("add_gang")
        return members

    def remove_task(self, task_id: str) -> bool:
        """Kill ONE task at runtime and forget it (dynamic mode only).
        Its terminal status then reports under an id no longer in the
        table and is ignored — deliberate: the removal was OUR
        decision, not a failure to react to."""
        if not self.dynamic:
            raise ClusterError("remove_task requires dynamic=True")
        with self._lock:
            task = self._find_task(task_id)
            if task is not None:
                self.tasks.remove(task)
        if task is None:
            return False
        self.log.info("dynamic task removed: %s", task)
        try:
            self.backend.kill(task_id)
        except Exception as e:
            self.log.warning("dynamic kill of %s failed: %s",
                             task_id[:8], e)
        return True

    def tasks_of(self, job_name: str) -> List[Task]:
        """Live tasks of one job (dynamic tiers poll this to converge
        actual toward target)."""
        with self._lock:
            return [t for t in self.tasks if t.job_name == job_name]

    def task_by_index(self, job_name: str, task_index: int) -> Optional[Task]:
        with self._lock:
            for t in self.tasks:
                if t.job_name == job_name and t.task_index == task_index:
                    return t
        return None

    def bump_generation(self) -> int:
        """Advance the fencing epoch (a blue-green rollout's shift
        token): tasks added AFTER the bump launch — and register — with
        the new generation; stragglers of older generations can be
        fenced at the registry."""
        with self._lock:
            self.generation += 1
            return self.generation

    def _dynamic_accept_loop(self) -> None:
        """Post-start rendezvous: accept registrations forever and hand
        each dynamic task its config per-connection — a Mode-B serving
        task only needs its OWN config to exec, so there is no gang
        barrier here."""
        while True:
            with self._lock:
                if self._stopped:
                    return
            try:
                conn, _ = self._listen.accept()
            except OSError:
                return          # listener closed (stop())
            with self._lock:
                stopped = self._stopped
            if stopped:
                # The shutdown poke (wire.wake_listener), not a task.
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._dynamic_handshake, args=(conn,),
                             name="dynamic-register", daemon=True).start()

    def _dynamic_config(self, task: Task) -> Dict[str, Any]:
        """The per-task config a dynamic registration receives — the
        same shape the gang broadcast sends, with membership computed
        from the live table (Mode-B serving tasks only read the env
        contract and ``cmd``)."""
        with self._lock:
            world = len(self.tasks)
            try:
                rank = self.tasks.index(task)
            except ValueError:
                rank = 0
            cluster_def: Dict[str, List[str]] = {}
            for t in self.tasks:
                cluster_def.setdefault(t.job_name, []).append(t.addr or "")
            gen = getattr(task, "generation", self.generation)
        return {
            "job_name": task.job_name, "task_index": task.task_index,
            "rank": rank, "world_size": world, "cpus": task.cpus,
            "mem": task.mem, "chips": task.chips, "cmd": task.cmd,
            "cwd": os.getcwd(), "cluster_def": cluster_def,
            "generation": gen, "coordinator": "",
            "forward_addresses": self.forward_addresses,
            "extra_config": self.extra_config, "protocol": self.protocol,
            "mesh_axes": self.mesh_axes or {}, "env": self.env,
        }

    def _dynamic_handshake(self, conn: socket.socket) -> None:
        """Serve ONE dynamic registration: validate (unknown/stale ids
        and stale generations dropped, exactly like the gang path),
        send the config, await the ack, mark the task initialized."""
        try:
            conn.settimeout(30.0)
            msg = wire.recv_msg(conn, self.token)
            if not (isinstance(msg, dict) and msg.get("op") == "register"):
                self.log.warning("unexpected dynamic rendezvous "
                                 "message: %r", msg)
                return
            task_id = msg.get("task_id", "")
            with self._lock:
                task = self._find_task(task_id)
                expect_gen = (getattr(task, "generation", self.generation)
                              if task is not None else None)
            if task is None:
                self.log.warning("dynamic registration from unknown/stale "
                                 "task id %s", task_id)
                return
            gen = msg.get("gen")
            if gen is not None:
                try:
                    gen = int(gen)
                except (TypeError, ValueError):
                    gen = -1
                if gen != expect_gen:
                    self.log.warning(
                        "dropping stale-generation dynamic registration "
                        "from task id %s (gen %s, expected %s)", task_id,
                        msg.get("gen"), expect_gen)
                    return
            wire.send_msg(conn, self._dynamic_config(task), self.token)
            ack = wire.recv_msg(conn, self.token)
            if ack != "ok":
                self.log.warning("dynamic task %s failed to ack: %r",
                                 task, ack)
                return
            with self._lock:
                task.addr = msg.get("addr")
                task.initialized = True
            self.log.info("dynamic task registered: %s", task)
        except (OSError, wire.WireError) as e:
            self.log.warning("dynamic registration failed: %s", e)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- bring-up ----------------------------------------------------------

    def start(self) -> None:
        """Bind rendezvous socket → start backend → event loop until every
        task registers → broadcast cluster config (reference start(),
        scheduler.py:320-369)."""
        self._listen = wire.bind_ephemeral()
        self.addr = wire.sock_addr(self._listen,
                                   advertise_host=os.environ.get("TPUMESOS_ADVERTISE_HOST"))
        self.log.info("rendezvous listening on %s", self.addr)
        if self.token_transport == "file":
            # Must exist before the first launch: tasks read it at startup.
            fd, path = tempfile.mkstemp(prefix="tpumesos-token-")
            with os.fdopen(fd, "w") as f:  # mkstemp creates mode 0600
                f.write(self.token)
            self._token_file = path
        self.backend.start(self)
        if self.restart_policy == "elastic":
            self._elastic_thread = threading.Thread(
                target=self._elastic_loop, name="elastic-recovery",
                daemon=True)
            self._elastic_thread.start()
        try:
            self._form_gang()
        except Exception:
            self.stop()
            raise
        if self.dynamic:
            # From here on registrations are served continuously: tasks
            # added by add_task() dial the same rendezvous address and
            # get their config per-connection, no gang barrier.
            t = threading.Thread(target=self._dynamic_accept_loop,
                                 name="dynamic-rendezvous", daemon=True)
            t.start()
            self._dynamic_thread = t

    def _form_gang(self) -> None:
        """Run the rendezvous loop until every task registered, then
        broadcast the cluster config — one gang formation, shared by the
        initial bring-up and every elastic re-formation."""
        sel = selectors.DefaultSelector()
        sel.register(self._listen, selectors.EVENT_READ, ("accept", None, None))
        deadline = time.monotonic() + self.start_timeout
        try:
            while True:
                with self._lock:
                    if self._fatal:
                        raise ClusterError(self._fatal)
                    if self._stopped:
                        raise ClusterError("scheduler stopped during bring-up")
                    if all(t.initialized for t in self.tasks):
                        break
                if time.monotonic() > deadline:
                    raise ClusterError(
                        f"cluster bring-up timed out after {self.start_timeout}s; "
                        f"uninitialized: "
                        f"{[t for t in self.tasks if not t.initialized]}")
                for key, _ in sel.select(timeout=0.5):
                    kind, conn, framer = key.data
                    if kind == "accept":
                        conn, _ = self._listen.accept()
                        conn.setblocking(False)
                        sel.register(conn, selectors.EVENT_READ,
                                     ("conn", conn, wire.Framer(self.token)))
                        continue
                    try:
                        data = conn.recv(65536)
                    except (BlockingIOError, InterruptedError):
                        continue
                    except OSError:
                        data = b""
                    if not data:
                        sel.unregister(conn)
                        if not self._connection_owned(conn):
                            conn.close()
                        continue
                    try:
                        msgs = framer.feed(data)
                    except wire.WireError as e:
                        self.log.warning("rejecting connection: %s", e)
                        sel.unregister(conn)
                        conn.close()
                        continue
                    for msg in msgs:
                        if self._handle_register(conn, msg):
                            sel.unregister(conn)
            self._start_cluster()
        finally:
            sel.close()

    def _connection_owned(self, conn: socket.socket) -> bool:
        return any(t.connection is conn for t in self.tasks)

    def _handle_register(self, conn: socket.socket, msg: Any) -> bool:
        """One task dialing back (reference: scheduler.py:341-361; task side
        server.py:25-27).  Returns True when the connection is claimed by a
        task and must leave the selector."""
        if not (isinstance(msg, dict) and msg.get("op") == "register"):
            self.log.warning("unexpected rendezvous message: %r", msg)
            return False
        gen = msg.get("gen")
        if gen is not None:
            # Generation fence: a zombie of a torn-down gang re-dialing
            # the rendezvous must never be adopted into the current one.
            try:
                gen = int(gen)
            except (TypeError, ValueError):
                gen = -1
            if gen != self.generation:
                self.log.warning(
                    "dropping stale-generation registration from task id %s "
                    "(gen %s, current %d)", msg.get("task_id"), msg.get("gen"),
                    self.generation)
                conn.close()
                return True
        task = self._find_task(msg.get("task_id", ""))
        if task is None:
            self.log.warning("registration from unknown/stale task id %s",
                             msg.get("task_id"))
            conn.close()
            return True
        with self._lock:
            task.addr = msg["addr"]
            task.coord_port = int(msg.get("coord_port") or 0)
            task.connection = conn
            task.initialized = True
        self.log.info("task registered: %s", task)
        return True

    def _start_cluster(self) -> None:
        """Broadcast per-task config and await acks (reference
        _start_tf_cluster, scheduler.py:288-318).

        The revive window closes here: once every task has registered and the
        broadcast begins, peers may already be acting on their config, so a
        task death during the broadcast is fatal (matching the reference,
        where a socket error in _start_tf_cluster aborts bring-up).
        """
        with self._lock:
            if not self.tasks:
                # Dynamic mode may start with an EMPTY table: there is no
                # gang to broadcast to; tasks added later get their
                # config per-registration from the dynamic rendezvous.
                self.started = True
                self.log.info("cluster started empty (dynamic): tasks "
                              "join at runtime via add_task()")
                return
            self._broadcasting = True
            # Snapshot connections under the lock: the revive path can close
            # and null task.connection from the status-watcher thread.
            conns = [(task, task.connection) for task in self.tasks]
            if any(conn is None for _, conn in conns):
                raise ClusterError("task lost between registration and broadcast")
            cluster_def = self.cluster_def
            generation = self.generation

        world_size = len(self.tasks)
        rank0 = self.tasks[0]
        coordinator = f"{rank0.addr.rsplit(':', 1)[0]}:{rank0.coord_port}"

        for rank, (task, conn) in enumerate(conns):
            conn.setblocking(True)
            conn.settimeout(self.start_timeout)
            config = {
                "job_name": task.job_name,
                "task_index": task.task_index,
                "rank": rank,
                "world_size": world_size,
                "cpus": task.cpus,
                "mem": task.mem,
                "chips": task.chips,
                "cmd": task.cmd,
                "cwd": os.getcwd(),
                "cluster_def": cluster_def,
                "generation": generation,
                "coordinator": coordinator,
                "forward_addresses": self.forward_addresses,
                "extra_config": self.extra_config,
                "protocol": self.protocol,
                "mesh_axes": self.mesh_axes or self._default_mesh_axes(),
                "env": self.env,
            }
            try:
                wire.send_msg(conn, config, self.token)
            except OSError as e:
                raise ClusterError(f"task {task} died during config broadcast: {e}")
        for task, conn in conns:
            try:
                ack = wire.recv_msg(conn, self.token)
            except (OSError, wire.WireError) as e:
                raise ClusterError(f"task {task} died before acking: {e}")
            if ack != "ok":
                raise ClusterError(f"task {task} failed to ack: {ack!r}")
            self.log.info("task %s ready", task)
            if task.cmd is not None:
                # Mode B: the control connection's job is done
                # (reference closes here for both modes, scheduler.py:318;
                # Mode A keeps it open as the SPMD dispatch channel).
                conn.close()
                task.connection = None
            else:
                # The bring-up timeout must not outlive bring-up: dispatched
                # functions run arbitrarily long (a whole training loop), so
                # the dispatch channel blocks indefinitely; a SIGKILLed peer
                # still surfaces promptly as EOF/ECONNRESET.
                conn.settimeout(None)
        with self._lock:
            if not all(t.initialized for t in self.tasks):
                # A terminal status raced the tail of the broadcast and
                # reset a task (the pre-start revive path): this gang is
                # not whole — better a loud formation failure (retried by
                # elastic recovery, fatal on initial bring-up) than
                # declaring a cluster started with a hole in it.
                raise ClusterError("task lost during config broadcast")
            self.started = True
            # Atomically with started=True: a recovery (if this formation
            # was one) is over the instant the gang is live.  Clearing
            # _recovering later (on the recovery thread) would leave a
            # window where a new-gang death hits the post-start branch
            # but _request_recovery still early-returns on the stale
            # flag — the incident would be recorded nowhere.
            self._recovering = False
            self._recover_reason = None
        self.log.info("cluster started: %d task(s), generation %d, "
                      "coordinator %s", world_size, generation, coordinator)

    def _default_mesh_axes(self) -> Dict[str, int]:
        """North-star mapping (BASELINE.json / SURVEY §2.7): ps jobs in the
        spec mean "shard the parameters", so the whole device set becomes an
        ``fsdp`` axis; workers-only means plain data parallelism.  -1 lets
        the runtime absorb however many devices actually exist."""
        has_ps = any(job.name == "ps" for job in self.task_spec)
        return {"fsdp": -1} if has_ps else {"dp": -1}

    # -- user-facing surface ----------------------------------------------

    @property
    def targets(self) -> Dict[str, str]:
        """Session-target map, kept for API parity with the reference
        (scheduler.py:279-286); the scheme reflects the data plane."""
        return {
            f"/job:{t.job_name}/task:{t.task_index}": f"{self.protocol}://{t.addr}"
            for t in self.tasks
        }

    @property
    def cluster_def(self) -> Dict[str, List[str]]:
        return {
            job.name: [t.addr for t in sorted(
                (t for t in self.tasks if t.job_name == job.name),
                key=lambda t: t.task_index)]
            for job in self.task_spec
        }

    def run(self, func: Any, *args: Any, **kwargs: Any) -> Any:
        """SPMD dispatch: run ``func`` on every Mode-A task and return the
        result from the lowest-ranked in-graph task (global rank 0 whenever
        rank 0 is a Mode-A task; in a mixed spec where rank 0 runs a cmd,
        the first dispatchable rank after it).

        This is the TPU-native successor of the reference's in-graph mode:
        where a TF driver placed ops with ``tf.device('/job:ps/task:0')`` and
        ran them through a remote session (examples/plus.py:23-33), a JAX
        driver ships one function that every process executes under the
        ``jax.distributed`` runtime; sharding — not device strings — decides
        placement.

        ``func`` may be a callable (resolved by module+qualname on the task,
        so it must be importable there — the scheduler's ``sys.path`` is
        forwarded, reference precedent scheduler.py:168-176) or an explicit
        ``"module:qualname"`` string.  Arguments must be JSON-serializable.
        """
        results = self.run_all(func, *args, **kwargs)
        return results[0]

    def run_on(self, ranks, func: Any, *args: Any, **kwargs: Any) -> List[Any]:
        """Targeted dispatch to a subset of tasks by global rank — the
        analogue of the reference's per-task op placement
        (``tf.device('/job:ps/task:k')``, matrix_factorization.py:21-28).

        Only for per-process work (IO, debugging, state inspection): a
        function that enters an XLA collective must run on EVERY process or
        the mesh deadlocks — use :meth:`run` / :meth:`run_all` for those.
        Results come back in the order of ``ranks``; an unknown or
        non-dispatchable rank is an error, not a silent skip.
        """
        return self._dispatch(func, args, kwargs, ranks=list(ranks))

    def run_all(self, func: Any, *args: Any, **kwargs: Any) -> List[Any]:
        return self._dispatch(func, args, kwargs, ranks=None)

    def _dispatch(self, func, args, kwargs, ranks) -> List[Any]:
        with self._lock:
            if self._fatal:
                raise ClusterError(self._fatal)
            if self._recovering:
                raise ClusterError(
                    f"cluster re-forming (generation {self.generation}): "
                    f"{self._recover_reason}")
            if not self.started:
                raise ClusterError("cluster not started")
            self._call_id += 1
            call_id = self._call_id
            generation = self.generation
        if self.chaos is not None:
            # Fault-injection trigger point: "kill task i at dispatch N"
            # is the deterministic stand-in for a mid-training preemption.
            self.chaos.event("scheduler.dispatch", key=str(call_id))
        spec = _func_spec(func)
        dispatchable = {rank: t for rank, t in enumerate(self.tasks)
                        if t.cmd is None and t.connection is not None}
        if ranks is None:
            mode_a = list(dispatchable.values())
        else:
            bad = [r for r in ranks if r not in dispatchable]
            if bad:
                raise ClusterError(
                    f"rank(s) {bad} are not connected in-graph tasks "
                    f"(dispatchable: {sorted(dispatchable)})")
            if len(set(ranks)) != len(ranks):
                raise ClusterError(
                    f"duplicate rank(s) in {ranks}: each dispatch targets a "
                    "rank at most once (call run_on again to repeat)")
            mode_a = [dispatchable[r] for r in ranks]  # request order
        if not mode_a:
            raise ClusterError("no in-graph (cmd=None) tasks to dispatch to")
        msg = {"op": "run", "call_id": call_id, "gen": generation,
               "func": spec, "args": list(args), "kwargs": kwargs}

        def _fatal_dispatch(why: str) -> ClusterError:
            # A dead peer or desynchronized channel poisons the whole SPMD
            # dispatch path: survivors may hold queued frames for this
            # call_id with no resync protocol, and a partially-delivered
            # collective would deadlock the mesh.  Fail-fast marks the
            # cluster fatal so finished()/run() fail fast and supervise()
            # can restart it; elastic turns the same signal into a gang
            # re-formation (the caller still sees ClusterError for THIS
            # call — it resumes after wait_ready()).
            with self._lock:
                self._post_start_failure(why)
            return ClusterError(why)

        task = None
        try:
            for task in mode_a:
                wire.send_msg(task.connection, msg, self.token)
            replies = self._drain_replies(mode_a, call_id, generation,
                                          _fatal_dispatch)
        except (OSError, wire.WireError) as e:
            raise _fatal_dispatch(
                f"task {task} lost during dispatch: {e}") from e
        results = []
        errors = []
        for task in mode_a:
            reply = replies[task.id]
            if not reply.get("ok"):
                errors.append(f"on {task}:\n{reply.get('error')}")
            results.append(reply.get("value"))
        if errors:
            raise RemoteError("remote failure " + "\n".join(errors))
        return results

    def _drain_replies(self, mode_a, call_id, generation, _fatal_dispatch):
        """Collect one reply per task, reading ALL connections concurrently.

        A blocking per-rank read would leave the caller stuck on a survivor
        (which may legitimately run for hours) while a dead peer's EOF goes
        unnoticed; a selector surfaces any death — via socket EOF or the
        status watcher flipping ``_fatal`` (or starting a recovery) —
        within a poll interval.  Replies stamped with a stale generation
        (a zombie of a previous gang flushing its last result) are logged
        and dropped, never matched against current call ids.
        """
        replies: Dict[str, dict] = {}
        sel = selectors.DefaultSelector()
        framers = {task.id: wire.Framer(self.token) for task in mode_a}
        try:
            for task in mode_a:
                try:
                    task.connection.setblocking(False)
                    sel.register(task.connection, selectors.EVENT_READ, task)
                except OSError as e:
                    # Attribute here: letting this escape to _dispatch's
                    # catch-all would blame the send loop's last task.
                    raise _fatal_dispatch(
                        f"task {task} lost during dispatch: {e}") from e
            while len(replies) < len(mode_a):
                events = sel.select(timeout=0.5)
                with self._lock:
                    if self._fatal:
                        raise ClusterError(self._fatal)
                    if self._recovering:
                        raise ClusterError(
                            f"cluster re-forming (generation "
                            f"{self.generation}): {self._recover_reason}")
                for key, _ in events:
                    task = key.data
                    try:
                        data = key.fileobj.recv(1 << 16)
                    except (BlockingIOError, InterruptedError):
                        continue
                    except OSError as e:
                        raise _fatal_dispatch(
                            f"task {task} lost during dispatch: {e}") from e
                    if not data:
                        raise _fatal_dispatch(
                            f"task {task} died during dispatch (EOF)")
                    try:
                        msgs = framers[task.id].feed(data)
                    except wire.WireError as e:
                        raise _fatal_dispatch(
                            f"bad frame from {task} during dispatch: {e}"
                        ) from e
                    for reply in msgs:
                        if (isinstance(reply, dict) and "gen" in reply
                                and reply["gen"] != generation):
                            self.log.warning(
                                "dropping stale-generation reply from %s: "
                                "gen %r (current %d)", task,
                                reply.get("gen"), generation)
                            continue
                        if (task.id in replies
                                or not (isinstance(reply, dict)
                                        and reply.get("call_id") == call_id)):
                            raise _fatal_dispatch(
                                f"bad reply from {task}: {reply!r}")
                        replies[task.id] = reply
                    if task.id in replies:
                        sel.unregister(key.fileobj)
        finally:
            sel.close()
            for task in mode_a:
                if task.connection is not None:
                    try:
                        task.connection.setblocking(True)
                        task.connection.settimeout(None)
                    except OSError:
                        pass
        return replies

    def finished(self) -> bool:
        """True when any job has fully TASK_FINISHED (reference semantics —
        all workers done ends the run even though ps tasks never exit,
        scheduler.py:474-477)."""
        with self._lock:
            if self._fatal:
                raise ClusterError(self._fatal)
            if self._recovering:
                # Mid-recovery nothing is finished: the next generation's
                # tasks re-run (from their checkpoints) and re-count.
                return False
            return any(
                self.job_finished.get(job.name, 0) >= (job.num - job.start)
                for job in self.task_spec
            )

    def join(self, poll: float = 0.1) -> None:
        """Block until ``finished()`` (tfrun's poll loop, tfrun:101-102)."""
        while not self.finished():
            time.sleep(poll)

    def stop(self) -> None:
        """Teardown (reference stop(), scheduler.py:459-472)."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            self._recover_event.set()   # unpark the elastic thread to exit
        if (self._elastic_thread is not None
                and self._elastic_thread is not threading.current_thread()):
            self._elastic_thread.join(timeout=5.0)
        if self._dynamic_thread is not None and self._listen is not None:
            # close() alone does not interrupt a blocked accept():
            # poke the rendezvous awake so the dynamic accept loop
            # exits NOW instead of burning its whole join timeout.
            wire.wake_listener(self._listen)
            try:
                self._listen.close()
            except OSError:
                pass
            self._dynamic_thread.join(timeout=5.0)
        for task in self.tasks:
            if task.connection is not None:
                try:
                    wire.send_msg(task.connection, {"op": "shutdown"}, self.token)
                except OSError:
                    pass
                try:
                    task.connection.close()
                except OSError:
                    pass
                task.connection = None
        self.backend.stop()
        if self._listen is not None:
            self._listen.close()
            self._listen = None
        if self._token_file is not None:
            try:
                os.unlink(self._token_file)
            except OSError:
                pass
            self._token_file = None
        self.log.info("scheduler stopped")


def _func_spec(func: Any) -> dict:
    if isinstance(func, str):
        module, _, qualname = func.partition(":")
        if not qualname:
            raise ValueError(f"func string must be 'module:qualname', got {func!r}")
        return {"module": module, "qualname": qualname, "path": None}
    module = getattr(func, "__module__", None)
    qualname = getattr(func, "__qualname__", None)
    if not module or not qualname or "<locals>" in qualname:
        raise ValueError(
            f"{func!r} is not addressable as module:qualname; define it at "
            f"module top level (lambdas/closures cannot be shipped)")
    path = None
    if module == "__main__":
        main_mod = sys.modules.get("__main__")
        path = getattr(main_mod, "__file__", None)
        if path is None:
            raise ValueError("cannot ship a __main__ function from an "
                             "interactive session; use 'module:qualname'")
        path = os.path.abspath(path)
    return {"module": module, "qualname": qualname, "path": path}
