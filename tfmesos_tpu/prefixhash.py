"""Chunk hashing shared by the serving prefix cache and the fleet router.

The prefix cache (serving.py) keys resident KV pages by a HASH CHAIN
over page-aligned token chunks: the digest of chunk j commits to every
token in chunks 0..j, which is exactly the dependency set of the K/V
values stored in page j (attention at position i reads all positions
<= i).  The fleet router computes the same chain over an incoming
request's prompt and matches it against the digests replicas advertise
in their registry heartbeats — so the two sides MUST agree on the
hashing, which is why it lives in this tiny jax-free module (the fleet
control plane imports no model code).

Geometry: a batcher with page size ``P`` and a constant batcher-level
prefix whose last ``off`` tokens share the first cacheable page splits
a prompt into chunks of ``first = P - off`` then ``P, P, ...`` tokens
(``first == P`` without a prefix tail), and seeds the chain with the
digest of those constant tail tokens so the chain stays a pure function
of what the GATEWAY can see — the request prompt — given the replica's
advertised ``(page, first, seed)``.  Only COMPLETE chunks enter the
chain: a trailing partial page's KV is never shared (its page also
receives the row's own decode writes).
"""

from __future__ import annotations

import hashlib
from typing import List, Sequence

import numpy as np

__all__ = ["token_bytes", "chunk_digest", "prompt_digests",
           "match_depth"]

_DIGEST_SIZE = 16


def token_bytes(tokens) -> bytes:
    """Canonical byte form of a token sequence (int32 little-endian —
    one encoding on both sides of the wire)."""
    return np.ascontiguousarray(
        np.asarray(tokens, np.int32)).tobytes()


def chunk_digest(parent: bytes, chunk_tokens) -> bytes:
    """Digest of one chunk given its parent chain digest."""
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    h.update(parent)
    h.update(token_bytes(chunk_tokens))
    return h.digest()


def prompt_digests(prompt, page: int, first: int = 0,
                   seed: bytes = b"") -> List[bytes]:
    """The chain digests of every COMPLETE page-aligned chunk of
    ``prompt``: chunk 0 is ``first`` tokens (default: ``page``), the
    rest ``page`` tokens each; the trailing partial chunk (if any) is
    dropped.  ``seed`` is the constant-prefix-tail digest described in
    the module docstring."""
    prompt = np.asarray(prompt, np.int32)
    if page < 1:
        raise ValueError(f"page must be >= 1, got {page}")
    first = first or page
    out: List[bytes] = []
    h = seed
    off, w = 0, first
    while off + w <= prompt.size:
        h = chunk_digest(h, prompt[off:off + w])
        out.append(h)
        off += w
        w = page
    return out


def match_depth(digests: Sequence[bytes], advertised) -> int:
    """Longest leading run of ``digests`` present in ``advertised`` (a
    set/sequence of digests, bytes or hex str): the number of leading
    chunks a replica's cache already holds.  The chain property makes a
    leading-run check sufficient — digest j can only be advertised by a
    cache that stored chunks 0..j."""
    adv = {d if isinstance(d, bytes) else bytes.fromhex(d)
           for d in advertised}
    depth = 0
    for d in digests:
        if d not in adv:
            break
        depth += 1
    return depth
