"""Node runtime: the process Mesos (or the local backend) launches per task.

Bootstrap contract matches the reference (server.py:14-49): dial the
scheduler's rendezvous address given on the command line, register, receive
the cluster config, ack — then enter one of two modes:

* **Mode A (in-graph successor)** — ``cmd is None``.  The reference started a
  ``tf.train.Server`` and parked forever (server.py:51-66), serving remotely
  placed ops.  There is no remote-session concept in JAX, so Mode A instead
  joins the ``jax.distributed`` runtime and serves an SPMD executor loop on
  the (kept-open) control connection: the driver ships a function reference,
  every process runs it, rank 0's result returns to the driver.
* **Mode B (between-graph)** — ``cmd`` set.  Exec the user command with the
  env contract and ``{placeholder}`` substitution, pumping child stdout to
  our stdout and optionally over TCP to the log collector, with
  initializer/finalizer hooks — the reference behavior (server.py:67-113) on
  the new transport.

Usage: ``python -m tfmesos_tpu.server <task_id> <scheduler_addr>``
(launch site: spec.Task.to_task_info; reference: scheduler.py:163-167).
"""

from __future__ import annotations

import importlib
import importlib.util
import json
import os
import socket
import subprocess
import sys
import traceback
from typing import Any, Dict, Optional

from tfmesos_tpu import wire
from tfmesos_tpu.runtime import TaskContext, initialize, task_env
from tfmesos_tpu.utils.logging import get_logger

log = get_logger("tfmesos_tpu.server")


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print("usage: python -m tfmesos_tpu.server <task_id> <scheduler_addr>",
              file=sys.stderr)
        return 2
    task_id, scheduler_addr = argv
    token = wire.load_token()
    # The gang generation this task was launched into (elastic recovery
    # bumps it per re-formation).  Echoed in the registration and every
    # Mode-A reply so the scheduler can fence out zombies of a dead gang.
    try:
        generation = int(os.environ.get("TPUMESOS_GENERATION", "0") or 0)
    except ValueError:
        generation = 0

    # Our own identity address (reference: server.py:18-21).  The listening
    # socket is identity only; control flows over the dial-back connection.
    listen = wire.bind_ephemeral()
    addr = wire.sock_addr(listen, advertise_host=os.environ.get("TPUMESOS_ADVERTISE_HOST"))

    # Reserve a port for the jax.distributed coordinator service; rank 0's
    # reservation becomes the cluster coordinator address.
    coord_sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    coord_sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    coord_sock.bind(("", 0))
    coord_port = coord_sock.getsockname()[1]

    sock = wire.connect(scheduler_addr)
    wire.send_msg(sock, {"op": "register", "task_id": task_id, "addr": addr,
                         "coord_port": coord_port, "gen": generation}, token)
    # The config broadcast only happens once EVERY task has registered, which
    # can be long after our own registration (peers may still be waiting for
    # resources) — so this wait gets its own generous timeout.
    sock.settimeout(float(os.environ.get("TPUMESOS_HANDSHAKE_TIMEOUT", "300")))
    config = wire.recv_msg(sock, token)
    log.info("task %s registered as %s:%s rank=%s", task_id[:8],
             config.get("job_name"), config.get("task_index"), config.get("rank"))

    # Only Mode B forwards child output; Mode A has no child to pump.
    forward_fd = _connect_forwarder(config) if config.get("cmd") is not None else None
    wire.send_msg(sock, "ok", token)

    coord_sock.close()  # free the reserved port just before anyone binds it
    listen.close()

    if config.get("cmd") is None:
        return _run_executor(sock, config, token)
    sock.close()
    return _run_cmd(config, forward_fd)


# -- Mode A: SPMD executor -------------------------------------------------


def _run_executor(sock: socket.socket, config: Dict[str, Any], token: str) -> int:
    ctx = TaskContext.from_config(config)
    os.environ.update(task_env(config))
    for key, value in (config.get("env") or {}).items():
        os.environ[str(key)] = str(value)
    if not ctx.extra_config.get("no_jax"):
        initialize(ctx)
    generation = int(config.get("generation", 0) or 0)
    sock.settimeout(None)
    while True:
        try:
            msg = wire.recv_msg(sock, token)
        except (wire.WireError, OSError):
            # Scheduler went away: teardown (reference Mode A parks until the
            # Mesos executor kills it; our exit is graceful).
            return 0
        if not isinstance(msg, dict):
            continue
        op = msg.get("op")
        if op == "shutdown":
            return 0
        if op != "run":
            log.warning("unknown op %r", op)
            continue
        if "gen" in msg and msg["gen"] != generation:
            # Generation fence, task side: a dispatch stamped for another
            # gang epoch must not execute here (a half-delivered collective
            # would deadlock the current mesh).  Drop it; the scheduler's
            # reply fence handles the mirror-image case.
            log.warning("dropping stale-generation dispatch (gen %r, ours "
                        "%d)", msg.get("gen"), generation)
            continue
        reply: Dict[str, Any] = {"op": "result", "call_id": msg.get("call_id"),
                                 "gen": generation}
        try:
            func = _resolve_func(msg["func"])
            value = func(ctx, *msg.get("args", ()), **msg.get("kwargs", {}))
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                value = repr(value)
            reply.update(ok=True, value=value)
        except BaseException:
            reply.update(ok=False, error=traceback.format_exc())
        try:
            wire.send_msg(sock, reply, token)
        except OSError:
            return 0


def _resolve_func(spec: Dict[str, Any]):
    module_name, qualname, path = spec["module"], spec["qualname"], spec.get("path")
    if path:
        # Function was defined in the driver's __main__ script: import that
        # file as a module (shared-filesystem assumption, same as the
        # reference's cwd forwarding, server.py:95-98).
        loaded = sys.modules.get("__tpumesos_driver__")
        if loaded is None or getattr(loaded, "__file__", None) != path:
            mod_spec = importlib.util.spec_from_file_location("__tpumesos_driver__", path)
            loaded = importlib.util.module_from_spec(mod_spec)
            sys.modules["__tpumesos_driver__"] = loaded
            mod_spec.loader.exec_module(loaded)
        target = loaded
    else:
        target = importlib.import_module(module_name)
    obj: Any = target
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


# -- Mode B: user command --------------------------------------------------


class _SafeDict(dict):
    """``str.format_map`` helper: leave unknown ``{placeholders}`` intact."""

    def __missing__(self, key: str) -> str:
        return "{" + key + "}"


def _substitute_cmd(cmd: str, config: Dict[str, Any]) -> str:
    """Reference placeholder contract (server.py:89-92) plus TPU-era keys."""
    cluster_def = config.get("cluster_def") or {}
    mapping = _SafeDict(
        ps_hosts=",".join(cluster_def.get("ps", [])),
        worker_hosts=",".join(cluster_def.get("worker", [])),
        job_name=config.get("job_name", ""),
        task_index=config.get("task_index", 0),
        rank=config.get("rank", 0),
        world_size=config.get("world_size", 1),
        coordinator=config.get("coordinator", ""),
    )
    return cmd.format_map(mapping)


def _connect_forwarder(config: Dict[str, Any]) -> Optional[socket.socket]:
    """Dial the log collector if this task's logs were requested
    (reference: server.py:41-46; collector side lives in cli.py)."""
    forward = (config.get("forward_addresses") or {})
    key = f"{config.get('job_name')}:{config.get('task_index')}"
    target = forward.get(key) or forward.get("*")
    if not target:
        return None
    try:
        return wire.connect(target, timeout=10.0)
    except OSError as e:
        log.warning("cannot reach log collector %s: %s", target, e)
        return None


def _run_cmd(config: Dict[str, Any], forward_fd: Optional[socket.socket]) -> int:
    extra = config.get("extra_config") or {}
    env = dict(os.environ)
    env.update(task_env(config))
    for key, value in (config.get("env") or {}).items():
        env[str(key)] = str(value)

    initializer = extra.get("initializer")
    if initializer:
        subprocess.check_call(initializer, shell=True, env=env)

    cmd = _substitute_cmd(config["cmd"], config)
    cwd = config.get("cwd")
    if cwd and not os.path.isdir(cwd):
        cwd = None  # no shared filesystem; run where we are
    log.info("exec: %s", cmd)
    proc = subprocess.Popen(cmd, shell=True, env=env, cwd=cwd,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    prefix = f"[{config.get('job_name')}:{config.get('task_index')}] ".encode()
    from tfmesos_tpu.logpump import pump_lines
    pump_lines(proc.stdout, sys.stdout.buffer,
               forward_fd.fileno() if forward_fd else -1, prefix)
    rc = proc.wait()

    finalizer = extra.get("finalizer")
    if finalizer:
        subprocess.check_call(finalizer, shell=True, env=env)
    if forward_fd is not None:
        try:
            forward_fd.close()
        except OSError:
            pass
    return rc


if __name__ == "__main__":
    sys.exit(main())
