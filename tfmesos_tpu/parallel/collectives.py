"""Collective helpers over the device mesh.

The reference delegates all tensor traffic to TensorFlow's gRPC runtime
(SURVEY §2.8); here the data plane is XLA collectives over ICI/DCN, and these
helpers are the small vocabulary the rest of the framework uses.  Everything
is a thin, named wrapper over ``jax.lax`` collectives so call sites read as
intent ("average gradients over dp") rather than mechanics.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

AxisName = Union[str, Sequence[str]]


def all_reduce_sum(x, axis: AxisName):
    return jax.lax.psum(x, axis_name=axis)


def all_reduce_mean(x, axis: AxisName):
    return jax.lax.pmean(x, axis_name=axis)


def grad_sync(grads, axis: AxisName):
    """Average a gradient pytree across the data-parallel axis — the GSPMD
    successor of PS apply-gradients (reference mnist_replica.py:116-157)."""
    return jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, axis_name=axis), grads)


def all_gather(x, axis: AxisName, *, axis_index: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, axis_index: int = 0):
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=axis_index,
                                tiled=True)


def ppermute_shift(x, axis: str, shift: int = 1):
    """Rotate values around a ring axis (the building block of ring attention
    and pipeline transfer); ``shift=+1`` sends to the next-higher index."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


def axis_size(axis: str) -> int:
    return jax.lax.axis_size(axis)


def barrier(axis: AxisName):
    """Cheap cross-device barrier: reduce a scalar nobody reads."""
    return jax.lax.psum(jnp.zeros((), jnp.float32), axis_name=axis)
