"""Collective helpers over the device mesh.

The reference delegates all tensor traffic to TensorFlow's gRPC runtime
(SURVEY §2.8); here the data plane is XLA collectives over ICI/DCN, and these
helpers are the small vocabulary the rest of the framework uses.  Everything
is a thin, named wrapper over ``jax.lax`` collectives so call sites read as
intent ("average gradients over dp") rather than mechanics.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from tfmesos_tpu.compat import axis_size

AxisName = Union[str, Sequence[str]]


def all_reduce_sum(x, axis: AxisName):
    return jax.lax.psum(x, axis_name=axis)


def all_reduce_mean(x, axis: AxisName):
    return jax.lax.pmean(x, axis_name=axis)


def grad_sync(grads, axis: AxisName):
    """Average a gradient pytree across the data-parallel axis — the GSPMD
    successor of PS apply-gradients (reference mnist_replica.py:116-157)."""
    return jax.tree_util.tree_map(lambda g: jax.lax.pmean(g, axis_name=axis), grads)


def all_gather(x, axis: AxisName, *, axis_index: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name=axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: AxisName, *, axis_index: int = 0):
    return jax.lax.psum_scatter(x, axis_name=axis, scatter_dimension=axis_index,
                                tiled=True)


def broadcast_replicated_grad(x, axis: AxisName):
    """Identity forward, ``psum`` backward — the input-side twin of
    :func:`psum_replicated_grad` (Megatron's *f* operator to its *g*).

    Use it where a tp-replicated activation FANS OUT into per-shard
    compute (e.g. ``h @ w1_columns``): each shard's backward produces
    only its columns' contribution to dL/dh, and the psum in the
    transpose reassembles the full cotangent.  Needed only when the
    stage is differentiated with ``jax.vjp`` inside a ``shard_map``
    (1F1B); outer differentiation through the shard_map inserts the
    same transpose automatically."""
    @jax.custom_vjp
    def _bcast(v):
        return v

    _bcast.defvjp(lambda v: (v, None),
                  lambda _, g: (jax.lax.psum(g, axis),))
    return _bcast(x)


def psum_replicated_grad(x, axis: AxisName):
    """``psum`` whose backward is the IDENTITY — for manual-collective
    stage bodies that are differentiated with ``jax.vjp`` INSIDE a
    ``shard_map`` (the 1F1B pipeline's in-loop backward).

    Math: for y = Σ_i x_i computed on every shard, dL/dx_i = dL/dy —
    the identity — whenever downstream consumes y uniformly across the
    axis (the Megatron row-parallel case, where the cotangent is
    replicated).  Plain ``lax.psum``'s transpose under
    ``check_vma=False`` manual mode cannot assume the cotangent is
    replicated and inserts another psum, scaling gradients by the axis
    size; differentiating THROUGH the shard_map from outside (the
    gpipe/circular route) does not hit this, which is why those
    schedules use plain psum.
    """
    @jax.custom_vjp
    def _psum(v):
        return jax.lax.psum(v, axis)

    _psum.defvjp(lambda v: (jax.lax.psum(v, axis), None),
                 lambda _, g: (g,))
    return _psum(x)


def ppermute_shift(x, axis: str, shift: int = 1):
    """Rotate values around a ring axis (the building block of ring attention
    and pipeline transfer); ``shift=+1`` sends to the next-higher index."""
    n = axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str):
    return jax.lax.axis_index(axis)


# axis_size is re-exported from tfmesos_tpu.compat (imported above): the
# jax-version-portable size of a named mesh axis.


def barrier(axis: AxisName):
    """Cheap cross-device barrier: reduce a scalar nobody reads."""
    return jax.lax.psum(jnp.zeros((), jnp.float32), axis_name=axis)
