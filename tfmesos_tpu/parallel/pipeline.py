"""Pipeline parallelism over the ``pp`` mesh axis.

Another axis the reference never had (SURVEY §2.7).  Layers are grouped into
stages whose parameters are *stacked* along a leading dim and sharded over
``pp`` — so each device holds one stage (or ``virtual_stages`` chunks of
one) — and microbatches flow through the ring with one ``ppermute`` hop per
tick.  All devices run every tick (SPMD).

Two schedules:

* ``"gpipe"`` — fill/drain; bubble fraction (S−1)/(M+S−1).
* ``"circular"`` — interleaved virtual stages: each device holds ``v``
  round-robin layer chunks and every microbatch laps the ring ``v`` times,
  shrinking the bubble to ≈(S−1)/(M·v) at the cost of v× more ppermute hops
  (tiny activations vs. the per-chunk matmuls they overlap with).

Composes with dp/fsdp (activations stay sharded on their batch dims) AND
with tp: the stage body runs inside the full-mesh ``shard_map``, so it may
freely use ``jax.lax.psum(..., "tp")``-style collectives, and
``param_partition`` shards each stage's weights over non-pp axes
(Megatron-style column/row splits).  What a stage must NOT do is open a
nested ``shard_map`` — write manual-collective stage bodies instead
(models/transformer.py:_block_manual_tp is the worked example).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading 'pp' dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_sharding_tree(stacked_params: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Each leaf's leading (stage) dim sharded over ``axis``."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis, *([None] * (p.ndim - 1)))),
        stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any], stacked_params: Any,
                   x, mesh: Mesh, axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   param_partition: Optional[Any] = None,
                   schedule: str = "gpipe", virtual_stages: int = 1,
                   with_aux: bool = False):
    """Run ``x`` through the stage pipeline; returns the final activations.

    ``stage_fn(params, h) -> h`` applies ONE stage chunk (same activation
    shape in and out); it runs inside the mesh-wide shard_map and may use
    manual collectives over non-pp axes.  ``stacked_params`` leaves have
    leading dim = number of chunks (``pp`` for gpipe,
    ``pp * virtual_stages`` for circular, in global layer order).  ``x`` is
    ``[B, ...]``, split into microbatches along B.  ``param_partition``
    (optional) is a pytree of PartitionSpecs for each leaf's NON-leading
    dims, e.g. ``P("tp", None)`` to column-shard a weight over tp.

    ``with_aux`` (default off) changes the stage contract to
    ``stage_fn(params, h) -> (h, aux)`` where ``aux`` is a pytree of fp32
    scalars (e.g. router-health metrics); the call then returns
    ``(out, aux_mean)`` with each scalar averaged over every chunk
    execution — all chunks × all microbatches × the data shards — i.e. the
    microbatched analogue of the non-pp path's mean-over-layers-and-batch.
    (Statistics that are nonlinear in the batch, like the load-balance
    loss's fraction·probability product, are computed per microbatch and
    averaged — the same estimator gradient accumulation uses.)  Pass the
    aux pytree's *structure* (any pytree, values ignored) as ``with_aux``;
    ``with_aux=True`` infers it by abstractly evaluating ``stage_fn``,
    which only works for stage bodies free of manual collectives.
    """
    n_stages = mesh.shape[axis]
    if schedule not in ("gpipe", "circular"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if virtual_stages > 1 and schedule != "circular":
        # Silently running gpipe over pp*v chunks would apply only the
        # first chunk on each device — wrong loss, no error.
        raise ValueError("virtual_stages > 1 requires schedule='circular'")
    aux_proto = None
    if with_aux is not False and with_aux is not True:
        aux_proto, with_aux = with_aux, True
    v = virtual_stages if schedule == "circular" else 1
    if n_stages == 1:
        n_chunks = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        def chunk(i):
            return jax.tree_util.tree_map(lambda p: p[i], stacked_params)
        h = x
        if not with_aux:
            for i in range(n_chunks):
                h = stage_fn(chunk(i), h)
            return h
        auxes = []
        for i in range(n_chunks):
            h, aux = stage_fn(chunk(i), h)
            auxes.append(aux)
        return h, jax.tree_util.tree_map(
            lambda *xs: jnp.mean(jnp.stack(xs), axis=0), *auxes)
    m = num_microbatches or n_stages
    d_axes = data_axes(mesh)
    d_axis_names = d_axes or ()
    dp_size = 1
    for a in d_axis_names:
        dp_size *= mesh.shape[a]
    if x.shape[0] % (m * dp_size):
        raise ValueError(f"batch {x.shape[0]} not divisible into {m} "
                         f"microbatches x {dp_size} data shards")
    if schedule == "circular":
        if v < 1:
            raise ValueError("virtual_stages must be >= 1")
        if m % n_stages:
            raise ValueError(f"circular schedule needs microbatches ({m}) "
                             f"divisible by pp ({n_stages})")
        # Chunk c of the round-robin assignment (device s runs chunks
        # lap*pp + s) must land at the device's local index `lap` under
        # contiguous sharding: permute global order [c] -> [s*v + lap].
        perm = jnp.asarray([(i % n_stages) * v + i // n_stages
                            for i in range(n_stages * v)]).argsort()
        stacked_params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, perm, axis=0), stacked_params)

    def local(params, xs):
        stage = jax.lax.axis_index(axis)
        b_loc = xs.shape[0]
        micro = xs.reshape(m, b_loc // m, *xs.shape[1:])
        mb_shape = micro.shape[1:]

        def chunk_params(lap):
            # local leading dim is v (1 for gpipe): pick this lap's chunk
            return jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(p, lap, 0,
                                                       keepdims=False),
                params)

        def run_stage(lap, h):
            out = stage_fn(chunk_params(lap), h)
            return out if with_aux else (out, {})

        def tick(t, carry):
            received, outputs, aux_acc = carry
            u = t - stage
            r = jnp.where(u >= 0, u % n_stages, 0)
            w = u - r
            lap = jnp.where(u >= 0, (w % (n_stages * v)) // n_stages, 0)
            mb = jnp.where(u >= 0, (w // (n_stages * v)) * n_stages + r, 0)
            active = (u >= 0) & (mb < m)
            inject = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(mb, 0, m - 1), 0, keepdims=False)
            h = jnp.where((stage == 0) & (lap == 0), inject, received)
            out, aux = run_stage(lap, h)
            # Inactive ticks run the stage on garbage; their aux is masked
            # out (the activation path needs no mask — inactive outputs are
            # never emitted and get overwritten as they ride the ring).
            aux_acc = jax.tree_util.tree_map(
                lambda acc, a: acc + jnp.where(active, a, 0.0), aux_acc, aux)
            emit = active & (stage == n_stages - 1) & (lap == v - 1)
            out_idx = jnp.clip(mb, 0, m - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(emit, out,
                          jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                                       keepdims=False)),
                out_idx, 0)
            received = ppermute_shift(out, axis, 1)
            return received, outputs, aux_acc

        outputs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        received0 = jnp.zeros(mb_shape, xs.dtype)
        aux0 = jax.tree_util.tree_map(
            lambda _: jnp.zeros((), jnp.float32),
            aux_proto if with_aux else {})
        _, outputs, aux_acc = jax.lax.fori_loop(
            0, m * v + n_stages - 1, tick, (received0, outputs0, aux0))
        # Results live on the last stage; broadcast them to every stage so
        # the caller sees a pp-replicated output.
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name=axis)
        out = outputs.reshape(b_loc, *xs.shape[1:])
        if not with_aux:
            return out
        # Mean over every chunk execution: each of the m microbatches runs
        # each of the n_stages*v chunks exactly once, spread over pp.
        aux_mean = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, axis) / (m * n_stages * v), aux_acc)
        # Average over the data shards (each ring works its own batch
        # shard); any remaining axis (tp/ep) already holds identical values
        # — stage bodies pmean/psum their collectives internally — so the
        # replicated out_spec is sound.
        if d_axis_names:
            aux_mean = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, d_axis_names), aux_mean)
        return out, aux_mean

    if param_partition is None:
        param_specs = jax.tree_util.tree_map(
            lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    else:
        param_specs = jax.tree_util.tree_map(
            lambda p, spec: P(axis, *spec), stacked_params, param_partition)
    # Activations shard over the data axes (each pipeline ring works on its
    # batch shard) and replicate over pp/tp, where the ring/psum handle them.
    x_spec = P(data_axes(mesh), *([None] * (x.ndim - 1)))
    if with_aux:
        if aux_proto is None:
            # Infer the aux structure abstractly (collective-free stages
            # only — pass the structure explicitly otherwise).
            aux_proto = jax.eval_shape(
                lambda p, h: stage_fn(
                    jax.tree_util.tree_map(lambda q: q[0], p), h)[1],
                stacked_params, jnp.zeros((x.shape[0] // (m * dp_size),)
                                          + x.shape[1:], x.dtype))
        out_specs = (x_spec, jax.tree_util.tree_map(lambda _: P(), aux_proto))
    else:
        out_specs = x_spec
    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(param_specs, x_spec), out_specs=out_specs,
                       check_vma=False)
    return fn(stacked_params, x)
