"""Pipeline parallelism over the ``pp`` mesh axis (GPipe-style).

Another axis the reference never had (SURVEY §2.7).  Layers are grouped into
stages whose parameters are *stacked* along a leading dim and sharded over
``pp`` — so each device holds one stage — and microbatches flow through the
ring with one ``ppermute`` hop per tick.  All devices run every tick (SPMD);
warm-up/drain bubbles are the usual GPipe cost, amortized by the microbatch
count.  Composes with dp/fsdp (batch axes) since activations stay sharded on
their batch dims.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading 'pp' dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_sharding_tree(stacked_params: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Each leaf's leading (stage) dim sharded over ``axis``."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis, *([None] * (p.ndim - 1)))),
        stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any], stacked_params: Any,
                   x, mesh: Mesh, axis: str = "pp",
                   num_microbatches: int = None):
    """Run ``x`` through the stage pipeline; returns the final activations.

    ``stage_fn(params, h) -> h`` applies ONE stage (same activation shape in
    and out).  ``stacked_params`` leaves have leading dim = number of stages.
    ``x`` is ``[B, ...]``; it is split into microbatches along B.
    """
    n_stages = mesh.shape[axis]
    if n_stages == 1:
        params0 = jax.tree_util.tree_map(lambda p: p[0], stacked_params)
        return stage_fn(params0, x)
    m = num_microbatches or n_stages
    d_axes = data_axes(mesh)
    dp_size = 1
    for a in (d_axes or ()):
        dp_size *= mesh.shape[a]
    if x.shape[0] % (m * dp_size):
        raise ValueError(f"batch {x.shape[0]} not divisible into {m} "
                         f"microbatches x {dp_size} data shards")

    def local(params, xs):
        params = jax.tree_util.tree_map(lambda p: jnp.squeeze(p, 0), params)
        stage = jax.lax.axis_index(axis)
        b_loc = xs.shape[0]
        micro = xs.reshape(m, b_loc // m, *xs.shape[1:])
        mb_shape = micro.shape[1:]

        def tick(t, carry):
            received, outputs = carry
            idx = jnp.minimum(t, m - 1)
            inject = jnp.where(t < m,
                               jax.lax.dynamic_index_in_dim(micro, idx, 0,
                                                            keepdims=False),
                               jnp.zeros(mb_shape, xs.dtype))
            h = jnp.where(stage == 0, inject, received)
            out = stage_fn(params, h)
            out_idx = t - (n_stages - 1)
            write = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, out,
                          jax.lax.dynamic_index_in_dim(
                              outputs, jnp.maximum(out_idx, 0), 0,
                              keepdims=False)),
                jnp.maximum(out_idx, 0), 0)
            received = ppermute_shift(out, axis, 1)
            return received, outputs

        outputs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        received0 = jnp.zeros(mb_shape, xs.dtype)
        _, outputs = jax.lax.fori_loop(0, m + n_stages - 1, tick,
                                       (received0, outputs0))
        # Results live on the last stage; broadcast them to every stage so
        # the caller sees a pp-replicated output.
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name=axis)
        return outputs.reshape(b_loc, *xs.shape[1:])

    param_specs = jax.tree_util.tree_map(
        lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    # Activations shard over the data axes (each pipeline ring works on its
    # batch shard) and replicate over pp, where the ring rotates them.
    x_spec = P(data_axes(mesh), *([None] * (x.ndim - 1)))
    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(param_specs, x_spec), out_specs=x_spec,
                       check_vma=False)
    return fn(stacked_params, x)
