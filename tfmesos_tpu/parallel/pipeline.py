"""Pipeline parallelism over the ``pp`` mesh axis.

Another axis the reference never had (SURVEY §2.7).  Layers are grouped into
stages whose parameters are *stacked* along a leading dim and sharded over
``pp`` — so each device holds one stage (or ``virtual_stages`` chunks of
one) — and microbatches flow through the ring with one ``ppermute`` hop per
tick.  All devices run every tick (SPMD).

Three schedules:

* ``"gpipe"`` — fill/drain; bubble fraction (S−1)/(M+S−1).
* ``"circular"`` — interleaved virtual stages: each device holds ``v``
  round-robin layer chunks and every microbatch laps the ring ``v`` times,
  shrinking the bubble to ≈(S−1)/(M·v) at the cost of v× more ppermute hops
  (tiny activations vs. the per-chunk matmuls they overlap with).
* 1F1B — same bubble as gpipe but forward and backward interleaved in one
  loop, bounding the live activation stash at S microbatch inputs instead
  of M.  Lives in :func:`pipeline_train_1f1b` (a fused train-step entry
  point) because autodiff of a forward-only schedule necessarily replays
  all-forwards-then-all-backwards.

Composes with dp/fsdp (activations stay sharded on their batch dims) AND
with tp: the stage body runs inside the full-mesh ``shard_map``, and
``param_partition`` shards each stage's weights over non-pp axes
(Megatron-style column/row splits).  What a stage must NOT do is open a
nested ``shard_map`` — write manual-collective stage bodies instead.
Under gpipe/circular (differentiated from OUTSIDE the shard_map) plain
``jax.lax.psum(..., "tp")`` collectives are fine
(models/transformer.py:_block_manual_tp is the worked example); under
1F1B the backward runs ``jax.vjp`` INSIDE the shard_map, where plain
psum's transpose double-counts — use the Megatron f/g pair
``collectives.broadcast_replicated_grad`` /
``collectives.psum_replicated_grad`` there (see
:func:`pipeline_train_1f1b`).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfmesos_tpu.compat import shard_map
from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading 'pp' dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_sharding_tree(stacked_params: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Each leaf's leading (stage) dim sharded over ``axis``."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis, *([None] * (p.ndim - 1)))),
        stacked_params)


def _schedule_1f1b(n_stages: int, m: int, v: int = 1):
    """Greedy 1F1B timetable, computed at trace time (all sizes static).

    Returns ``(kind, mb, lap)`` int arrays of shape [T, S]: at tick t
    device s performs kind 0=idle / 1=forward / 2=backward on microbatch
    mb of its LOCAL chunk ``lap`` (global virtual chunk = lap*S + s; lap
    is always 0 at v=1).  The policy generalizes the classic one: device
    d keeps at most ``(S - d) + (v - 1)*S`` microbatch-chunks in flight
    (its interleaved warmup depth), prefers the ready backward with the
    lowest microbatch (deepest chunk on ties), and fills with the ready
    forward with the lowest microbatch (earliest chunk on ties).  At
    v=1 this is exactly the classic schedule: same bubble as gpipe,
    peak stash S microbatch inputs.  At v>1 every microbatch laps the
    ring v times (chunk c feeds chunk c+1, always one device to the
    right), shrinking the FILL/DRAIN bubble for v x more ppermute hops
    — worth ~1.2x wall at bubble-bound shapes (deep pipe, few
    microbatches; bench.pipeline_bubble_stats measures this timetable
    statically), and ~nothing once m >> pp amortizes the fill.
    """
    import numpy as np

    n_virt = n_stages * v
    last = n_virt - 1
    next_f = [0] * n_virt
    next_b = [0] * n_virt
    f_done = [[-1] * m for _ in range(n_virt)]
    b_done = [[-1] * m for _ in range(n_virt)]
    kinds, mbs, laps = [], [], []
    t = 0
    while any(nb < m for nb in next_b):
        # The last VIRTUAL chunk never runs a separate forward tick: its
        # backward recomputes the chunk inside the loss vjp anyway, so a
        # standalone forward would be discarded work.  Its "forward" is
        # the ARRIVAL of the previous chunk's output (immediate for a
        # 1-chunk pipeline, whose chunk-0 input is always at hand).
        while next_f[last] < m and (
                last == 0 or 0 <= f_done[last - 1][next_f[last]] < t):
            f_done[last][next_f[last]] = (
                t if last == 0 else f_done[last - 1][next_f[last]] + 1)
            next_f[last] += 1
        krow = [0] * n_stages
        mrow = [0] * n_stages
        lrow = [0] * n_stages
        for d in range(n_stages):
            chunks = [lap * n_stages + d for lap in range(v)]
            ready_b, ready_f = [], []
            for c in chunks:
                i, j = next_b[c], next_f[c]
                if i < m and (
                        (c == last and 0 <= f_done[c][i] <= t)
                        or (c < last and 0 <= b_done[c + 1][i] < t)):
                    ready_b.append(c)
                # Per-chunk in-flight stays under S so the mb%S stash
                # slots of one chunk never collide.
                if (c < last and j < m
                        and (c == 0 or 0 <= f_done[c - 1][j] < t)
                        and j - next_b[c] < n_stages):
                    ready_f.append(c)
            inflight = sum(next_f[c] - next_b[c] for c in chunks)
            depth = (n_stages - d) + (v - 1) * n_stages
            if ready_b and (inflight >= depth or not ready_f):
                c = min(ready_b, key=lambda c_: (next_b[c_], -c_))
                krow[d], mrow[d], lrow[d] = 2, next_b[c], c // n_stages
                b_done[c][next_b[c]] = t
                next_b[c] += 1
            elif ready_f and inflight < depth:
                c = min(ready_f, key=lambda c_: (next_f[c_], c_))
                krow[d], mrow[d], lrow[d] = 1, next_f[c], c // n_stages
                f_done[c][next_f[c]] = t
                next_f[c] += 1
        kinds.append(krow)
        mbs.append(mrow)
        laps.append(lrow)
        t += 1
        if t > 4 * v * (m + n_virt) + 8:  # safety: must terminate
            raise AssertionError("1f1b schedule did not converge")
    return (np.asarray(kinds, np.int32), np.asarray(mbs, np.int32),
            np.asarray(laps, np.int32))


def pipeline_train_1f1b(stage_fn: Callable[[Any, Any], Any],
                        loss_fn: Callable[..., Any],
                        stacked_params: Any, x, targets, mesh: Mesh,
                        axis: str = "pp",
                        num_microbatches: Optional[int] = None,
                        param_partition: Optional[Any] = None,
                        tail_params: Any = None,
                        tail_partition: Optional[Any] = None,
                        stage_aux: bool = False,
                        virtual_stages: int = 1,
                        seq_axis: Optional[str] = None):
    """One fused forward+backward pipeline pass on the 1F1B schedule.

    ``pipeline_apply`` is forward-only — under ``jax.grad`` autodiff
    replays its reverse, which is gpipe's all-forwards-then-all-backwards
    with every microbatch's activations live.  1F1B's point is the
    bounded stash, and that is only expressible with forward and backward
    interleaved in ONE loop — hence a training-step entry point rather
    than a ``schedule=`` flag.

    ``stage_fn(chunk_params, h) -> h`` as in ``pipeline_apply``.  Manual
    non-pp collectives are allowed, with one 1F1B-specific rule: the
    backward runs ``jax.vjp`` INSIDE the shard_map, where a plain
    ``lax.psum``'s transpose double-counts over its axis — use the
    Megatron f/g pair ``collectives.broadcast_replicated_grad`` (where a
    replicated activation fans out into per-shard compute) and
    ``collectives.psum_replicated_grad`` (after row-parallel matmuls),
    which carry their own transposes (tested:
    ``test_pipeline_1f1b_with_manual_tp_stage``).
    ``loss_fn(h_out, target_mb) -> scalar``
    (a per-microbatch MEAN, so the microbatch average equals the full
    batch loss).  Returns ``(loss, grads, dx)``: the mean loss, fp32
    parameter gradients with the stacked params' structure and sharding,
    and the gradient w.r.t. ``x`` (for an embedding layer upstream).
    ``targets`` are constants — no cotangent flows to them.

    ``tail_params`` (optional) are weights used INSIDE the loss — a final
    norm and unembedding head, say.  The loss contract becomes
    ``loss_fn(tail_params, h_out, target_mb)``, the tail rides into
    every stage (only the last differentiates it), and the return grows
    to ``(loss, grads, tail_grads, dx)`` with fp32 ``tail_grads``.
    ``tail_partition`` (optional) gives per-leaf PartitionSpecs for the
    tail — e.g. a vocab-sharded unembedding consumed by an in-body
    vocab-parallel CE (``ops/layers.vocab_parallel_ce_inbody``); leaves
    default to replicated, and tail grads keep the same specs.

    ``stage_aux=True`` changes the stage contract to
    ``stage_fn(chunk_params, h) -> (h, aux)`` where ``aux`` is a SCALAR
    auxiliary loss the stage contributes to the objective (e.g. MoE
    router load-balance/z losses, pre-weighted and normalized so the sum
    over stages is the model's aux term).  Each chunk's aux joins the
    loss at its BACKWARD tick: the vjp seeds the aux output with the
    same 1/m cotangent as the main loss, so router gradients flow even
    though no cotangent arrives from downstream stages, and the
    returned loss includes every stage's aux (summed over pp).

    ``virtual_stages=v`` (> 1) runs the INTERLEAVED timetable: device d
    owns chunks d, d+S, ..., every microbatch laps the ring v times, and
    each tick is 1/v the compute — shrinking the fill/drain bubble's
    wall-clock share by ~v for v x more (activation-sized) ppermute
    hops.  Stage-chunk grads return in the caller's GLOBAL chunk order.

    Memory: backward recomputes its chunk from the stashed chunk INPUT
    (standard 1F1B remat); each device holds at most S microbatch
    inputs PER LOCAL CHUNK (buffers of v*S slots — at v=1 the classic
    O(S) stash), independent of the microbatch count m.
    """
    if axis not in mesh.shape:
        raise ValueError(f"pipeline_train_1f1b: mesh {dict(mesh.shape)} has "
                         f"no {axis!r} axis (a size-1 axis is fine)")
    n_stages = mesh.shape[axis]
    m = num_microbatches or max(n_stages, 1)
    d_axis_names = data_axes(mesh) or ()
    dp_size = 1
    for a in d_axis_names:
        dp_size *= mesh.shape[a]
    if x.shape[0] % (m * dp_size):
        raise ValueError(f"batch {x.shape[0]} not divisible into {m} "
                         f"microbatches x {dp_size} data shards")
    if targets.shape[0] != x.shape[0]:
        raise ValueError(f"targets batch {targets.shape[0]} != x batch "
                         f"{x.shape[0]}")
    v = int(virtual_stages)
    if v < 1:
        raise ValueError("virtual_stages must be >= 1")
    if v > 1 and n_stages < 2:
        raise ValueError("interleaved virtual stages need a real pp axis "
                         "(n_stages >= 2); v chunks on one device is just "
                         "a deeper stage")
    n_chunks = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_chunks != max(n_stages, 1) * v:
        raise ValueError(f"1f1b runs {v} chunk(s) per stage: stacked "
                         f"params have {n_chunks} chunks for {n_stages} "
                         f"stages x virtual_stages={v}")
    if v > 1:
        # Interleaved layout: global chunk c runs on device c % S at
        # local index (lap) c // S.  Contiguous pp sharding gives device
        # d the local block [d*v, (d+1)*v), so permute global order
        # [c] -> [ (c % S)*v + c // S ] — same move as the circular
        # schedule — and inverse-permute the returned grads.
        perm = jnp.asarray([(i % n_stages) * v + i // n_stages
                            for i in range(n_stages * v)]).argsort()
        inv_perm = jnp.argsort(perm)
        stacked_params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, perm, axis=0), stacked_params)

    kinds_np, mbs_np, laps_np = _schedule_1f1b(max(n_stages, 1), m, v)
    ticks = kinds_np.shape[0]

    def local(params, tail, xs, ts):
        stage = jax.lax.axis_index(axis) if n_stages > 1 else 0
        b_loc = xs.shape[0]
        micro = xs.reshape(m, b_loc // m, *xs.shape[1:])
        tmicro = ts.reshape(m, b_loc // m, *ts.shape[1:])
        mb_shape = micro.shape[1:]
        kinds = jnp.asarray(kinds_np)
        mbs = jnp.asarray(mbs_np)
        laps = jnp.asarray(laps_np)
        slots = max(n_stages, 1)

        def tick(t, carry):
            (h_buf, g_buf, dparams, dtail, dx, loss_acc, recv_f,
             recv_g) = carry
            kind = kinds[t, stage]
            mb = mbs[t, stage]
            lap = laps[t, stage]
            slot = lap * slots + mb % slots
            if v == 1:
                # lap is constantly 0: slice once, outside the hot loop's
                # dataflow, instead of a per-tick O(params) gather.
                chunk_p = jax.tree_util.tree_map(lambda p: p[0], params)
            else:
                chunk_p = jax.tree_util.tree_map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, lap, 0, keepdims=False), params)
            # File the values that arrived over the ring: what they are is
            # the neighbour's op last tick, read from the same table.
            # Chunk c always feeds chunk c+1 one device to the right (c-1
            # one left for cotangents); crossing the ring seam bumps the
            # receiving lap (device 0 receives lap l as chunk lap l+1,
            # device S-1 receives backward lap l as chunk lap l-1).
            prev_s = (stage - 1) % slots
            next_s = (stage + 1) % slots
            if n_stages > 1:
                up_kind = jnp.where(t > 0, kinds[t - 1, prev_s], 0)
                up_mb = mbs[jnp.maximum(t - 1, 0), prev_s]
                up_lap = laps[jnp.maximum(t - 1, 0), prev_s] + \
                    jnp.where(stage == 0, 1, 0)
                up_ok = (up_kind == 1) & ((stage > 0) | (up_lap < v))
                h_buf = jnp.where(
                    up_ok,
                    jax.lax.dynamic_update_index_in_dim(
                        h_buf, recv_f,
                        jnp.minimum(up_lap, v - 1) * slots
                        + up_mb % slots, 0), h_buf)
                dn_kind = jnp.where(t > 0, kinds[t - 1, next_s], 0)
                dn_mb = mbs[jnp.maximum(t - 1, 0), next_s]
                dn_lap = laps[jnp.maximum(t - 1, 0), next_s] - \
                    jnp.where(stage == slots - 1, 1, 0)
                dn_ok = (dn_kind == 2) & ((stage < slots - 1)
                                          | (dn_lap >= 0))
                g_buf = jnp.where(
                    dn_ok,
                    jax.lax.dynamic_update_index_in_dim(
                        g_buf, recv_g,
                        jnp.maximum(dn_lap, 0) * slots
                        + dn_mb % slots, 0), g_buf)

            z_send = jnp.zeros(mb_shape, xs.dtype)

            def do_idle(_):
                return (h_buf, dparams, dtail, dx, loss_acc, z_send, z_send)

            def do_fwd(_):
                # Compute one chunk forward; stash the chunk INPUT (the
                # 1F1B remat residual) and send the output down the ring.
                # (The aux scalar is recomputed — and differentiated — at
                # the chunk's backward tick; forward drops it.)
                inject = jax.lax.dynamic_index_in_dim(micro, mb, 0,
                                                      keepdims=False)
                h_in = jnp.where(
                    (stage == 0) & (lap == 0), inject,
                    jax.lax.dynamic_index_in_dim(h_buf, slot, 0,
                                                 keepdims=False))
                h_out = stage_fn(chunk_p, h_in)
                if stage_aux:
                    h_out = h_out[0]
                return (jax.lax.dynamic_update_index_in_dim(h_buf, h_in,
                                                            slot, 0),
                        dparams, dtail, dx, loss_acc, h_out, z_send)

            def do_bwd(_):
                # Recompute this chunk from the stashed input and vjp it.
                # The last stage seeds from the loss (cotangent 1/m);
                # earlier stages consume the cotangent off the ring.
                # Stage 0's stash IS the microbatch input — read it from
                # the (always-resident) batch, not the buffer, so the
                # 1-stage pipeline needs no forward ticks at all.
                inject = jax.lax.dynamic_index_in_dim(micro, mb, 0,
                                                      keepdims=False)
                h_stash = jnp.where(
                    (stage == 0) & (lap == 0), inject,
                    jax.lax.dynamic_index_in_dim(h_buf, slot, 0,
                                                 keepdims=False))
                tgt = jax.lax.dynamic_index_in_dim(tmicro, mb, 0,
                                                   keepdims=False)
                g_in = jax.lax.dynamic_index_in_dim(g_buf, slot, 0,
                                                    keepdims=False)

                def apply_stage(p, h):
                    """(h_out, aux): aux is 0 for plain stages, so one
                    code path serves both contracts."""
                    out = stage_fn(p, h)
                    if stage_aux:
                        return out[0], out[1].astype(jnp.float32)
                    return out, jnp.zeros((), jnp.float32)

                def last_chunk(_):
                    if tail_params is None:
                        def f(p, h):
                            out, aux = apply_stage(p, h)
                            return loss_fn(out, tgt).astype(jnp.float32) \
                                + aux
                        lval, vjp = jax.vjp(f, chunk_p, h_stash)
                        dp, dh = vjp(jnp.asarray(1.0 / m, lval.dtype))
                        dtl = zero_tail
                    else:
                        def f(p, h, tl):
                            out, aux = apply_stage(p, h)
                            return loss_fn(tl, out, tgt).astype(
                                jnp.float32) + aux
                        lval, vjp = jax.vjp(f, chunk_p, h_stash, tail)
                        dp, dh, dtl = vjp(jnp.asarray(1.0 / m, lval.dtype))
                        # fp32 like the other accumulators — and both cond
                        # branches must agree on dtypes (zero_tail is fp32).
                        dtl = jax.tree_util.tree_map(
                            lambda g: g.astype(jnp.float32), dtl)
                    return lval.astype(jnp.float32), dp, dh, dtl

                def mid_chunk(_):
                    (_, aux), vjp = jax.vjp(apply_stage, chunk_p, h_stash)
                    # The aux output takes the SAME 1/m seed as the loss:
                    # router grads flow from this stage's own aux term
                    # even though no loss cotangent arrives from the ring.
                    dp, dh = vjp((g_in, jnp.asarray(1.0 / m, jnp.float32)))
                    # Raw aux into the accumulator — the final /m turns the
                    # sum over microbatches into the mean, exactly as the
                    # last stage's lval.
                    return aux, dp, dh, zero_tail

                lval, dp, dh, dtl = jax.lax.cond(
                    (stage == slots - 1) & (lap == v - 1),
                    last_chunk, mid_chunk, None)

                def acc_at_lap(acc, g):
                    cur = jax.lax.dynamic_index_in_dim(acc, lap, 0,
                                                       keepdims=False)
                    return jax.lax.dynamic_update_index_in_dim(
                        acc, cur + g.astype(jnp.float32), lap, 0)

                new_dparams = jax.tree_util.tree_map(acc_at_lap, dparams,
                                                     dp)
                new_dtail = jax.tree_util.tree_map(
                    lambda acc, g: acc + g.astype(jnp.float32), dtail, dtl)
                new_dx = jnp.where(
                    (stage == 0) & (lap == 0),
                    jax.lax.dynamic_update_index_in_dim(
                        dx, dh.astype(dx.dtype), mb, 0), dx)
                return (h_buf, new_dparams, new_dtail, new_dx,
                        loss_acc + lval, z_send, dh.astype(xs.dtype))

            (h_buf, dparams, dtail, dx, loss_acc, send_f,
             send_g) = jax.lax.switch(kind, (do_idle, do_fwd, do_bwd), None)
            if n_stages > 1:
                recv_f = ppermute_shift(send_f, axis, 1)
                recv_g = ppermute_shift(send_g, axis, -1)
            return (h_buf, g_buf, dparams, dtail, dx, loss_acc, recv_f,
                    recv_g)

        h_buf0 = jnp.zeros((v * slots,) + mb_shape, xs.dtype)
        g_buf0 = jnp.zeros((v * slots,) + mb_shape, xs.dtype)
        dparams0 = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)
        zero_tail = jax.tree_util.tree_map(
            lambda p: jnp.zeros(jnp.shape(p), jnp.float32), tail)
        dx0 = jnp.zeros((m,) + mb_shape, jnp.float32)
        z = jnp.zeros(mb_shape, xs.dtype)
        carry = (h_buf0, g_buf0, dparams0, zero_tail, dx0,
                 jnp.zeros((), jnp.float32), z, z)
        carry = jax.lax.fori_loop(0, ticks, tick, carry)
        _, _, dparams, dtail, dx, loss_acc, _, _ = carry
        if n_stages > 1:
            # Every stage's loss_acc contributes (mid stages hold their
            # own aux terms; 0 for plain stages, so this reduces to the
            # last-stage-only extraction for dense models); tail grads
            # live on the last stage, dx on stage 0 — pp-broadcast them
            # so the caller sees pp-replicated outputs.  dparams stay
            # per-stage (that IS their sharding).
            loss = jax.lax.psum(loss_acc, axis)
            dtail = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(
                    jnp.where(stage == slots - 1, g, jnp.zeros_like(g)),
                    axis), dtail)
            dx = jax.lax.psum(
                jnp.where(stage == 0, dx, jnp.zeros_like(dx)), axis)
        else:
            loss = loss_acc
        loss = loss / m
        if d_axis_names:
            # Each data shard ran its own batch slice: the global loss is
            # the shard mean, and so are the parameter grads (each shard
            # holds d(local mean)/dp; the mean of those is d(global
            # mean)/dp).  dx stays per-shard (it IS the local slice) but
            # rescales to global-mean semantics: d(local mean)/dx is
            # dp_size times d(global mean)/dx.
            loss = jax.lax.pmean(loss, d_axis_names)
            dparams = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, d_axis_names), dparams)
            dtail = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, d_axis_names), dtail)
            dx = dx / dp_size
        return loss, dparams, dtail, dx.reshape(b_loc, *xs.shape[1:])

    if param_partition is None:
        param_specs = jax.tree_util.tree_map(
            lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    else:
        param_specs = jax.tree_util.tree_map(
            lambda p, spec: P(axis, *spec), stacked_params, param_partition)
    # seq_axis shards dim 1 (sequence): stage bodies see local shards and
    # handle the axis manually (einsum-ring attention, f/g-fanned weights,
    # an sp-reduced loss tail — see transformer.train_step_1f1b).
    x_spec = P(data_axes(mesh), seq_axis, *([None] * (x.ndim - 2)))
    t_spec = P(data_axes(mesh), seq_axis,
               *([None] * (targets.ndim - 2)))
    if tail_partition is None:
        tail_specs = jax.tree_util.tree_map(lambda _: P(), tail_params)
    else:
        tail_specs = jax.tree_util.tree_map(
            lambda _, s: s, tail_params, tail_partition,
            is_leaf=lambda n: isinstance(n, P))
    fn = shard_map(local, mesh=mesh,
                       in_specs=(param_specs, tail_specs, x_spec, t_spec),
                       out_specs=(P(), param_specs, tail_specs, x_spec),
                       check_vma=False)
    loss, grads, tail_grads, dx = fn(stacked_params, tail_params, x, targets)
    if v > 1:
        # Grads came back in the interleaved (permuted) chunk order;
        # restore the caller's global layer order.
        grads = jax.tree_util.tree_map(
            lambda g: jnp.take(g, inv_perm, axis=0), grads)
    if tail_params is None:
        return loss, grads, dx
    return loss, grads, tail_grads, dx


def pipeline_apply(stage_fn: Callable[[Any, Any], Any], stacked_params: Any,
                   x, mesh: Mesh, axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   param_partition: Optional[Any] = None,
                   schedule: str = "gpipe", virtual_stages: int = 1,
                   with_aux: bool = False,
                   seq_axis: Optional[str] = None):
    """Run ``x`` through the stage pipeline; returns the final activations.

    ``stage_fn(params, h) -> h`` applies ONE stage chunk (same activation
    shape in and out); it runs inside the mesh-wide shard_map and may use
    manual collectives over non-pp axes.  ``stacked_params`` leaves have
    leading dim = number of chunks (``pp`` for gpipe,
    ``pp * virtual_stages`` for circular, in global layer order).  ``x`` is
    ``[B, ...]``, split into microbatches along B.  ``param_partition``
    (optional) is a pytree of PartitionSpecs for each leaf's NON-leading
    dims, e.g. ``P("tp", None)`` to column-shard a weight over tp.

    ``with_aux`` (default off) changes the stage contract to
    ``stage_fn(params, h) -> (h, aux)`` where ``aux`` is a pytree of fp32
    scalars (e.g. router-health metrics); the call then returns
    ``(out, aux_mean)`` with each scalar averaged over every chunk
    execution — all chunks × all microbatches × the data shards — i.e. the
    microbatched analogue of the non-pp path's mean-over-layers-and-batch.
    (Statistics that are nonlinear in the batch, like the load-balance
    loss's fraction·probability product, are computed per microbatch and
    averaged — the same estimator gradient accumulation uses.)  Pass the
    aux pytree's *structure* (any pytree, values ignored) as ``with_aux``;
    ``with_aux=True`` infers it by abstractly evaluating ``stage_fn``,
    which only works for stage bodies free of manual collectives.

    ``seq_axis`` (optional) shards the activations' dim 1 (sequence)
    over that mesh axis: stage bodies then see LOCAL sequence shards
    and must handle the axis manually (e.g. the einsum-ring attention
    of ``models/transformer._block(sp_axis=...)`` with global rope
    positions); aux scalars additionally pmean over it (per-shard
    router statistics are an estimator of the full-sequence ones, like
    the microbatch estimator).
    """
    n_stages = mesh.shape[axis]
    if schedule not in ("gpipe", "circular"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if virtual_stages > 1 and schedule != "circular":
        # Silently running gpipe over pp*v chunks would apply only the
        # first chunk on each device — wrong loss, no error.
        raise ValueError("virtual_stages > 1 requires schedule='circular'")
    aux_proto = None
    if with_aux is not False and with_aux is not True:
        aux_proto, with_aux = with_aux, True
    v = virtual_stages if schedule == "circular" else 1
    if n_stages == 1:
        n_chunks = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        def chunk(i):
            return jax.tree_util.tree_map(lambda p: p[i], stacked_params)
        h = x
        if not with_aux:
            for i in range(n_chunks):
                h = stage_fn(chunk(i), h)
            return h
        auxes = []
        for i in range(n_chunks):
            h, aux = stage_fn(chunk(i), h)
            auxes.append(aux)
        return h, jax.tree_util.tree_map(
            lambda *xs: jnp.mean(jnp.stack(xs), axis=0), *auxes)
    m = num_microbatches or n_stages
    d_axes = data_axes(mesh)
    d_axis_names = d_axes or ()
    dp_size = 1
    for a in d_axis_names:
        dp_size *= mesh.shape[a]
    if x.shape[0] % (m * dp_size):
        raise ValueError(f"batch {x.shape[0]} not divisible into {m} "
                         f"microbatches x {dp_size} data shards")
    if schedule == "circular":
        if v < 1:
            raise ValueError("virtual_stages must be >= 1")
        if m % n_stages:
            raise ValueError(f"circular schedule needs microbatches ({m}) "
                             f"divisible by pp ({n_stages})")
        # Chunk c of the round-robin assignment (device s runs chunks
        # lap*pp + s) must land at the device's local index `lap` under
        # contiguous sharding: permute global order [c] -> [s*v + lap].
        perm = jnp.asarray([(i % n_stages) * v + i // n_stages
                            for i in range(n_stages * v)]).argsort()
        stacked_params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, perm, axis=0), stacked_params)

    def local(params, xs):
        stage = jax.lax.axis_index(axis)
        b_loc = xs.shape[0]
        micro = xs.reshape(m, b_loc // m, *xs.shape[1:])
        mb_shape = micro.shape[1:]

        def chunk_params(lap):
            # local leading dim is v (1 for gpipe): pick this lap's chunk
            return jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(p, lap, 0,
                                                       keepdims=False),
                params)

        def run_stage(lap, h):
            out = stage_fn(chunk_params(lap), h)
            return out if with_aux else (out, {})

        def tick(t, carry):
            received, outputs, aux_acc = carry
            u = t - stage
            r = jnp.where(u >= 0, u % n_stages, 0)
            w = u - r
            lap = jnp.where(u >= 0, (w % (n_stages * v)) // n_stages, 0)
            mb = jnp.where(u >= 0, (w // (n_stages * v)) * n_stages + r, 0)
            active = (u >= 0) & (mb < m)
            inject = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(mb, 0, m - 1), 0, keepdims=False)
            h = jnp.where((stage == 0) & (lap == 0), inject, received)
            out, aux = run_stage(lap, h)
            # Inactive ticks run the stage on garbage; their aux is masked
            # out (the activation path needs no mask — inactive outputs are
            # never emitted and get overwritten as they ride the ring).
            aux_acc = jax.tree_util.tree_map(
                lambda acc, a: acc + jnp.where(active, a, 0.0), aux_acc, aux)
            emit = active & (stage == n_stages - 1) & (lap == v - 1)
            out_idx = jnp.clip(mb, 0, m - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(emit, out,
                          jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                                       keepdims=False)),
                out_idx, 0)
            received = ppermute_shift(out, axis, 1)
            return received, outputs, aux_acc

        outputs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        received0 = jnp.zeros(mb_shape, xs.dtype)
        aux0 = jax.tree_util.tree_map(
            lambda _: jnp.zeros((), jnp.float32),
            aux_proto if with_aux else {})
        _, outputs, aux_acc = jax.lax.fori_loop(
            0, m * v + n_stages - 1, tick, (received0, outputs0, aux0))
        # Results live on the last stage; broadcast them to every stage so
        # the caller sees a pp-replicated output.
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name=axis)
        out = outputs.reshape(b_loc, *xs.shape[1:])
        if not with_aux:
            return out
        # Mean over every chunk execution: each of the m microbatches runs
        # each of the n_stages*v chunks exactly once, spread over pp.
        aux_mean = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, axis) / (m * n_stages * v), aux_acc)
        # Average over the data shards (each ring works its own batch
        # shard) and over seq_axis shards when the sequence is split
        # (per-shard router statistics estimate the full-sequence ones);
        # any remaining axis (tp/ep) already holds identical values —
        # stage bodies pmean/psum their collectives internally — so the
        # replicated out_spec is sound.
        red_axes = tuple(d_axis_names) + (
            (seq_axis,) if seq_axis else ())
        if red_axes:
            aux_mean = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, red_axes), aux_mean)
        return out, aux_mean

    if param_partition is None:
        param_specs = jax.tree_util.tree_map(
            lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    else:
        param_specs = jax.tree_util.tree_map(
            lambda p, spec: P(axis, *spec), stacked_params, param_partition)
    # Activations shard over the data axes (each pipeline ring works on its
    # batch shard) — plus the sequence dim over seq_axis when given — and
    # replicate over pp/tp, where the ring/psum handle them.
    x_spec = P(data_axes(mesh), seq_axis, *([None] * (x.ndim - 2)))
    sp_size = mesh.shape.get(seq_axis, 1) if seq_axis else 1
    if with_aux:
        if aux_proto is None:
            # Infer the aux structure abstractly (collective-free stages
            # only — pass the structure explicitly otherwise).
            aux_proto = jax.eval_shape(
                lambda p, h: stage_fn(
                    jax.tree_util.tree_map(lambda q: q[0], p), h)[1],
                stacked_params,
                jnp.zeros((x.shape[0] // (m * dp_size),
                           x.shape[1] // sp_size) + x.shape[2:], x.dtype))
        out_specs = (x_spec, jax.tree_util.tree_map(lambda _: P(), aux_proto))
    else:
        out_specs = x_spec
    fn = shard_map(local, mesh=mesh,
                       in_specs=(param_specs, x_spec), out_specs=out_specs,
                       check_vma=False)
    return fn(stacked_params, x)
