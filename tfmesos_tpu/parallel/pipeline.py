"""Pipeline parallelism over the ``pp`` mesh axis.

Another axis the reference never had (SURVEY §2.7).  Layers are grouped into
stages whose parameters are *stacked* along a leading dim and sharded over
``pp`` — so each device holds one stage (or ``virtual_stages`` chunks of
one) — and microbatches flow through the ring with one ``ppermute`` hop per
tick.  All devices run every tick (SPMD).

Two schedules:

* ``"gpipe"`` — fill/drain; bubble fraction (S−1)/(M+S−1).
* ``"circular"`` — interleaved virtual stages: each device holds ``v``
  round-robin layer chunks and every microbatch laps the ring ``v`` times,
  shrinking the bubble to ≈(S−1)/(M·v) at the cost of v× more ppermute hops
  (tiny activations vs. the per-chunk matmuls they overlap with).

Composes with dp/fsdp (activations stay sharded on their batch dims) AND
with tp: the stage body runs inside the full-mesh ``shard_map``, so it may
freely use ``jax.lax.psum(..., "tp")``-style collectives, and
``param_partition`` shards each stage's weights over non-pp axes
(Megatron-style column/row splits).  What a stage must NOT do is open a
nested ``shard_map`` — write manual-collective stage bodies instead
(models/transformer.py:_block_manual_tp is the worked example).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def stack_stage_params(stage_params: Sequence[Any]) -> Any:
    """Stack per-stage parameter pytrees along a new leading 'pp' dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def stage_sharding_tree(stacked_params: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Each leaf's leading (stage) dim sharded over ``axis``."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, P(axis, *([None] * (p.ndim - 1)))),
        stacked_params)


def pipeline_apply(stage_fn: Callable[[Any, Any], Any], stacked_params: Any,
                   x, mesh: Mesh, axis: str = "pp",
                   num_microbatches: Optional[int] = None,
                   param_partition: Optional[Any] = None,
                   schedule: str = "gpipe", virtual_stages: int = 1):
    """Run ``x`` through the stage pipeline; returns the final activations.

    ``stage_fn(params, h) -> h`` applies ONE stage chunk (same activation
    shape in and out); it runs inside the mesh-wide shard_map and may use
    manual collectives over non-pp axes.  ``stacked_params`` leaves have
    leading dim = number of chunks (``pp`` for gpipe,
    ``pp * virtual_stages`` for circular, in global layer order).  ``x`` is
    ``[B, ...]``, split into microbatches along B.  ``param_partition``
    (optional) is a pytree of PartitionSpecs for each leaf's NON-leading
    dims, e.g. ``P("tp", None)`` to column-shard a weight over tp.
    """
    n_stages = mesh.shape[axis]
    if schedule not in ("gpipe", "circular"):
        raise ValueError(f"unknown schedule {schedule!r}")
    if virtual_stages > 1 and schedule != "circular":
        # Silently running gpipe over pp*v chunks would apply only the
        # first chunk on each device — wrong loss, no error.
        raise ValueError("virtual_stages > 1 requires schedule='circular'")
    v = virtual_stages if schedule == "circular" else 1
    if n_stages == 1:
        n_chunks = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        def chunk(i):
            return jax.tree_util.tree_map(lambda p: p[i], stacked_params)
        h = x
        for i in range(n_chunks):
            h = stage_fn(chunk(i), h)
        return h
    m = num_microbatches or n_stages
    d_axes = data_axes(mesh)
    dp_size = 1
    for a in (d_axes or ()):
        dp_size *= mesh.shape[a]
    if x.shape[0] % (m * dp_size):
        raise ValueError(f"batch {x.shape[0]} not divisible into {m} "
                         f"microbatches x {dp_size} data shards")
    if schedule == "circular":
        if v < 1:
            raise ValueError("virtual_stages must be >= 1")
        if m % n_stages:
            raise ValueError(f"circular schedule needs microbatches ({m}) "
                             f"divisible by pp ({n_stages})")
        # Chunk c of the round-robin assignment (device s runs chunks
        # lap*pp + s) must land at the device's local index `lap` under
        # contiguous sharding: permute global order [c] -> [s*v + lap].
        perm = jnp.asarray([(i % n_stages) * v + i // n_stages
                            for i in range(n_stages * v)]).argsort()
        stacked_params = jax.tree_util.tree_map(
            lambda p: jnp.take(p, perm, axis=0), stacked_params)

    def local(params, xs):
        stage = jax.lax.axis_index(axis)
        b_loc = xs.shape[0]
        micro = xs.reshape(m, b_loc // m, *xs.shape[1:])
        mb_shape = micro.shape[1:]

        def chunk_params(lap):
            # local leading dim is v (1 for gpipe): pick this lap's chunk
            return jax.tree_util.tree_map(
                lambda p: jax.lax.dynamic_index_in_dim(p, lap, 0,
                                                       keepdims=False),
                params)

        def tick(t, carry):
            received, outputs = carry
            u = t - stage
            r = jnp.where(u >= 0, u % n_stages, 0)
            w = u - r
            lap = jnp.where(u >= 0, (w % (n_stages * v)) // n_stages, 0)
            mb = jnp.where(u >= 0, (w // (n_stages * v)) * n_stages + r, 0)
            active = (u >= 0) & (mb < m)
            inject = jax.lax.dynamic_index_in_dim(
                micro, jnp.clip(mb, 0, m - 1), 0, keepdims=False)
            h = jnp.where((stage == 0) & (lap == 0), inject, received)
            out = stage_fn(chunk_params(lap), h)
            emit = active & (stage == n_stages - 1) & (lap == v - 1)
            out_idx = jnp.clip(mb, 0, m - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(emit, out,
                          jax.lax.dynamic_index_in_dim(outputs, out_idx, 0,
                                                       keepdims=False)),
                out_idx, 0)
            received = ppermute_shift(out, axis, 1)
            return received, outputs

        outputs0 = jnp.zeros((m,) + mb_shape, xs.dtype)
        received0 = jnp.zeros(mb_shape, xs.dtype)
        _, outputs = jax.lax.fori_loop(0, m * v + n_stages - 1, tick,
                                       (received0, outputs0))
        # Results live on the last stage; broadcast them to every stage so
        # the caller sees a pp-replicated output.
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name=axis)
        return outputs.reshape(b_loc, *xs.shape[1:])

    if param_partition is None:
        param_specs = jax.tree_util.tree_map(
            lambda p: P(axis, *([None] * (p.ndim - 1))), stacked_params)
    else:
        param_specs = jax.tree_util.tree_map(
            lambda p, spec: P(axis, *spec), stacked_params, param_partition)
    # Activations shard over the data axes (each pipeline ring works on its
    # batch shard) and replicate over pp/tp, where the ring/psum handle them.
    x_spec = P(data_axes(mesh), *([None] * (x.ndim - 1)))
    fn = jax.shard_map(local, mesh=mesh,
                       in_specs=(param_specs, x_spec), out_specs=x_spec,
                       check_vma=False)
    return fn(stacked_params, x)
