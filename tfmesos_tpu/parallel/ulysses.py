"""Ulysses-style sequence parallelism: all-to-all over the ``sp`` axis.

The second of the two standard long-context strategies (beside ring
attention — the reference has neither, SURVEY §2.7/§5).  Activations arrive
sequence-sharded ``[B, T/sp, H, D]``; one ``all_to_all`` re-shards them from
the sequence dim to the heads dim, so every device runs EXACT attention over
the full sequence for its ``H/sp`` heads; a second ``all_to_all`` swaps the
sharding back.  Per device that is two a2a hops per attention call (three —
q, stacked K/V, output — on the grouped-query path, which moves H/KV-fold
fewer K/V bytes in exchange) versus the ring's ``sp`` ppermute hops —
cheaper on ICI whenever heads divide evenly — while the flash kernel sees
full-length sequences (its causal block skipping works globally, where the
ring must mask per shard).

Trade-offs vs ring attention (both exact):

* Ulysses needs ``n_heads % sp == 0``; the ring has no head constraint.
* Ulysses peak activation is O(T) per device for 1/sp of the heads (the
  full-sequence view exists only inside the attention call); the ring
  keeps everything at O(T/sp).  For sequences that fit, Ulysses wins on
  collective volume; for extreme lengths the ring is the memory-safe pick.
* A2a rides ICI as one fused collective; the ring pipelines hops behind
  compute.  Measure on the target topology (``bench.py``); model code
  flips with ``TransformerConfig(sp_impl="ulysses")``.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfmesos_tpu.compat import axis_size, shard_map
from tfmesos_tpu.parallel.sharding import data_axes


def ulysses_attention_local(q, k, v, axis: str = "sp", causal: bool = True,
                            scale: Optional[float] = None,
                            interpret: bool = False,
                            use_pallas: Optional[bool] = None,
                            window: Optional[int] = None):
    """Per-device body (call inside ``shard_map`` with ``axis`` in scope).

    Local shapes ``[B, T/sp, H, D]`` in, same out.  ``all_to_all`` with
    ``tiled=True`` splits the head dim across the group and concatenates
    the gathered sequence shards — after the hop each device holds
    ``[B, T, H/sp, D]`` and attention is an ordinary single-device call
    (the Pallas flash kernel on TPU) — so a sliding ``window`` passes
    straight through to it.
    """
    from tfmesos_tpu.ops.attention import flash_attention

    sp = axis_size(axis)
    h, hk = q.shape[2], k.shape[2]
    if h % sp:
        raise ValueError(f"ulysses needs heads ({h}) divisible by the sp "
                         f"axis ({sp}); use ring attention instead")

    if hk != h and hk % sp == 0:
        # GQA at kv width: separate hops for q and the stacked K/V pair —
        # the K/V a2a moves h/hk-fold fewer bytes, and splitting both head
        # dims sp-ways keeps local grouping aligned with the global
        # mapping (q head s·H/sp + j ↔ kv head s·KV/sp + j//g), which the
        # GQA-native flash kernel consumes directly.
        qh = jax.lax.all_to_all(q, axis, split_axis=2, concat_axis=1,
                                tiled=True)
        kv = jax.lax.all_to_all(jnp.stack((k, v)), axis, split_axis=3,
                                concat_axis=2, tiled=True)
        o = flash_attention(qh, kv[0], kv[1], causal=causal, scale=scale,
                            interpret=interpret, use_pallas=use_pallas,
                            window=window)
        return jax.lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    if hk != h:
        # GQA with sp not dividing kv_heads: broadcast up first.
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    # One stacked hop for q/k/v (dims shift by the stack dim), one for the
    # output — the documented two collectives per attention call.
    qkv = jax.lax.all_to_all(jnp.stack((q, k, v)), axis, split_axis=3,
                             concat_axis=2, tiled=True)
    qh, kh, vh = qkv[0], qkv[1], qkv[2]
    o = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                        interpret=interpret, use_pallas=use_pallas,
                        window=window)
    return jax.lax.all_to_all(o, axis, split_axis=1, concat_axis=2,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                      causal: bool = True, scale: Optional[float] = None,
                      interpret: bool = False,
                      use_pallas: Optional[bool] = None,
                      window: Optional[int] = None):
    """Sharded entry point: q/k/v are global ``[B, T, H, D]`` arrays with T
    sharded over ``axis``; falls back to plain flash/reference attention
    when the mesh has no (non-trivial) ``axis``."""
    from tfmesos_tpu.ops.attention import flash_attention

    if axis not in mesh.shape or mesh.shape[axis] == 1:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=interpret, use_pallas=use_pallas,
                               window=window)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(data_axes(mesh), axis, None, None)
    body = lambda q_, k_, v_: ulysses_attention_local(
        q_, k_, v_, axis=axis, causal=causal, scale=scale,
        interpret=interpret, use_pallas=use_pallas, window=window)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
