"""Device-mesh construction: the GSPMD successor of the ps/worker ClusterSpec.

The reference turns ``-s/-w`` job counts into a ``cluster_def`` of gRPC
addresses (scheduler.py:288-318).  Here those counts become mesh axis sizes:
the data-parallel axis replaces the worker set, and parameter sharding over
the ``fsdp`` axis replaces parameter servers (north star in BASELINE.json).
Richer axes — ``tp`` (tensor), ``pp`` (pipeline), ``sp`` (sequence/context),
``ep`` (expert) — are first-class so the same mesh scales past the
reference's PS world.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# Canonical axis order: collectives that ride ICI fastest should be innermost
# (contiguous device ids on a TPU slice share links); dp outermost so
# cross-slice DCN traffic, if any, is pure gradient all-reduce.
AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass
class MeshSpec:
    """An ordered mapping of axis name → size."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for name, size in self.axes.items():
            if size < 1:
                raise ValueError(f"axis {name!r} must have positive size, got {size}")

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def ordered(self) -> List[str]:
        known = [a for a in AXIS_ORDER if a in self.axes]
        extra = [a for a in self.axes if a not in AXIS_ORDER]
        return known + extra

    def shape(self) -> List[int]:
        return [self.axes[a] for a in self.ordered()]


def split_dcn_axes(axes: Dict[str, int]):
    """Split a flat axis dict into (ici_axes, dcn_axes): keys prefixed
    ``dcn.`` name the across-slice dims.  The prefix convention lets one
    dict ride the whole existing plumbing (scheduler kwarg → config
    broadcast → env var → ``TaskContext.mesh``)."""
    ici = {a: s for a, s in axes.items() if not a.startswith("dcn.")}
    dcn = {a[len("dcn."):]: s for a, s in axes.items() if a.startswith("dcn.")}
    return ici, dcn


def build_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all global
    devices).

    With ``axes=None`` the whole device set becomes one data-parallel axis —
    the direct analogue of "N workers" in the reference.  Any one axis may be
    given size -1 to absorb the remaining devices.  Axis names prefixed
    ``dcn.`` (e.g. ``{"dcn.dp": 2, "dp": 2, "tp": 2}``) lay that portion of
    the axis ACROSS pod slices — see :func:`build_hybrid_mesh`.
    """
    import jax
    from jax.sharding import Mesh
    import numpy as np

    if axes:
        ici, dcn = split_dcn_axes(axes)
        if dcn:
            return build_hybrid_mesh(ici, dcn, devices=devices)
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = devices.size

    if not axes:
        return Mesh(devices.reshape(n), ("dp",))

    spec = dict(axes)
    wildcards = [a for a, s in spec.items() if s == -1]
    if len(wildcards) > 1:
        raise ValueError(f"at most one axis may be -1, got {wildcards}")
    if wildcards:
        fixed = math.prod(s for s in spec.values() if s != -1)
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {spec}")
        spec[wildcards[0]] = n // fixed

    ms = MeshSpec(spec)
    if ms.size != n:
        raise ValueError(f"mesh {spec} wants {ms.size} devices, have {n}")
    names = tuple(ms.ordered())
    return Mesh(devices.reshape([spec[a] for a in names]), names)


def _slice_groups(devices, num_slices: Optional[int]):
    """Partition devices into slices (the ICI domains of a multi-slice pod).

    Real TPU devices carry ``slice_index`` (or at least ``process_index``);
    ``num_slices`` overrides with contiguous equal groups — the only option
    on virtual CPU meshes, where every device shares process 0.
    """
    devices = list(devices)
    if num_slices is not None:
        if num_slices < 1 or len(devices) % num_slices:
            raise ValueError(f"{len(devices)} devices not divisible into "
                             f"{num_slices} slices")
        per = len(devices) // num_slices
        return [devices[i * per:(i + 1) * per] for i in range(num_slices)]

    def slice_id(d):
        # CPU devices may advertise slice_index (always 0 — there is no
        # ICI), which would collapse a multi-process CPU runtime into one
        # "slice"; the process boundary is the meaningful domain there.
        v = getattr(d, "slice_index", None)
        if v is None or getattr(d, "platform", "") == "cpu":
            return d.process_index
        return v

    ids = sorted({slice_id(d) for d in devices})
    groups = [[d for d in devices if slice_id(d) == s] for s in ids]
    if len({len(g) for g in groups}) != 1:
        raise ValueError("uneven slice sizes: "
                         f"{ {s: len(g) for s, g in zip(ids, groups)} }")
    return groups


def build_hybrid_mesh(ici_axes: Dict[str, int], dcn_axes: Dict[str, int],
                      devices=None, num_slices: Optional[int] = None):
    """Mesh over a multi-slice pod: ``dcn_axes`` span slices (traffic over
    those axes rides the data-center network), ``ici_axes`` lay out within
    each slice (traffic rides ICI).

    The returned mesh merges the two: an axis named in both gets size
    ``dcn * ici`` with the DCN dim outermost — so e.g.
    ``ici_axes={"dp": 2, "tp": 4}, dcn_axes={"dp": 4}`` on a 4-slice pod
    gives ``{"dp": 8, "tp": 4}`` where tp collectives never cross DCN and
    the dp all-reduce hierarchically reduces intra-slice first (XLA does
    this automatically when the outer dim of an axis spans slices).  This
    is the standard scaling recipe: model axes (tp/sp/ep/pp) inside the
    slice, pure-gradient dp across slices.

    The reference scaled across hosts only through its PS/worker gRPC
    world (SURVEY §2.8); this is the TPU-native equivalent surface for
    "more hosts than one slice".
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if any(s == -1 for s in dcn_axes.values()):
        raise ValueError("dcn axes need explicit sizes (no -1 wildcard): "
                         "the slice count is what they must match")
    groups = _slice_groups(devices, num_slices)
    dcn = MeshSpec(dict(dcn_axes))
    has_identity = any(getattr(d, "slice_index", None) is not None
                       and getattr(d, "platform", "") != "cpu"
                       for d in devices)
    if num_slices is None and not has_identity and len(groups) == 1 \
            and dcn.size > 1 and len(devices) % dcn.size == 0:
        # Multiple slices requested but the devices carry no slice identity
        # at all (virtual/CPU platforms): fall back to contiguous equal
        # groups (what the forced-platform test meshes need).  Real TPUs
        # always expose slice_index, so a genuine single-slice system with
        # a multi-slice request still errors below instead of silently
        # running "DCN" axes over ICI.
        groups = _slice_groups(devices, dcn.size)
    n_slices, per_slice = len(groups), len(groups[0])

    ici_axes = dict(ici_axes)
    wild = [a for a, s in ici_axes.items() if s == -1]
    if len(wild) > 1:
        raise ValueError(f"at most one axis may be -1, got {wild}")
    if wild:
        fixed = math.prod(s for s in ici_axes.values() if s != -1)
        if fixed == 0 or per_slice % fixed:
            raise ValueError(f"{per_slice} devices per slice not divisible "
                             f"by fixed ici axes {ici_axes}")
        ici_axes[wild[0]] = per_slice // fixed
    ici = MeshSpec(ici_axes)
    if dcn.size != n_slices:
        raise ValueError(f"dcn axes {dcn_axes} want {dcn.size} slices, "
                         f"have {n_slices}")
    if ici.size != per_slice:
        raise ValueError(f"ici axes {ici_axes} want {ici.size} devices per "
                         f"slice, have {per_slice}")

    merged = MeshSpec({a: dcn_axes.get(a, 1) * ici_axes.get(a, 1)
                       for a in {**dcn_axes, **ici_axes}})
    names = merged.ordered()
    dcn_shape = [dcn_axes.get(a, 1) for a in names]
    ici_shape = [ici_axes.get(a, 1) for a in names]

    arr = np.array(groups, dtype=object)           # [n_slices, per_slice]
    arr = arr.reshape(dcn_shape + ici_shape)
    k = len(names)
    # Interleave (dcn_i, ici_i) pairs, then merge each pair into one dim
    # — the DCN dim lands outermost within every merged axis.
    arr = arr.transpose([d for i in range(k) for d in (i, k + i)])
    arr = arr.reshape([dcn_shape[i] * ici_shape[i] for i in range(k)])
    return Mesh(arr, tuple(names))


def mesh_from_jobs(jobs: Sequence, chips_per_task: int = 1) -> MeshSpec:
    """Map the reference's job spec onto mesh axes (north star: ``-w`` →
    data-parallel axis; ``-s`` > 0 enables parameter sharding, i.e. the PS
    role collapses into FSDP).

    Total devices = worker tasks × chips each.  When server/ps tasks exist,
    the mesh gets an ``fsdp`` axis over which parameters shard; its size is
    the full device count (pure FSDP) — matching "PS variables sharded over
    all of ICI" rather than a literal ps count, which has no TPU meaning.
    """
    nworker = sum(j.num - j.start for j in jobs if j.name == "worker")
    nps = sum(j.num - j.start for j in jobs if j.name == "ps")
    if nworker == 0:  # generic jobs: everything data-parallel
        total = sum((j.num - j.start) * max(1, chips_per_task) for j in jobs)
        return MeshSpec({"dp": max(1, total)})
    devices = nworker * max(1, chips_per_task)
    if nps > 0:
        return MeshSpec({"fsdp": devices})
    return MeshSpec({"dp": devices})
