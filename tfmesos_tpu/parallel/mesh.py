"""Device-mesh construction: the GSPMD successor of the ps/worker ClusterSpec.

The reference turns ``-s/-w`` job counts into a ``cluster_def`` of gRPC
addresses (scheduler.py:288-318).  Here those counts become mesh axis sizes:
the data-parallel axis replaces the worker set, and parameter sharding over
the ``fsdp`` axis replaces parameter servers (north star in BASELINE.json).
Richer axes — ``tp`` (tensor), ``pp`` (pipeline), ``sp`` (sequence/context),
``ep`` (expert) — are first-class so the same mesh scales past the
reference's PS world.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

# Canonical axis order: collectives that ride ICI fastest should be innermost
# (contiguous device ids on a TPU slice share links); dp outermost so
# cross-slice DCN traffic, if any, is pure gradient all-reduce.
AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")


@dataclass
class MeshSpec:
    """An ordered mapping of axis name → size."""

    axes: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for name, size in self.axes.items():
            if size < 1:
                raise ValueError(f"axis {name!r} must have positive size, got {size}")

    @property
    def size(self) -> int:
        return math.prod(self.axes.values()) if self.axes else 1

    def ordered(self) -> List[str]:
        known = [a for a in AXIS_ORDER if a in self.axes]
        extra = [a for a in self.axes if a not in AXIS_ORDER]
        return known + extra

    def shape(self) -> List[int]:
        return [self.axes[a] for a in self.ordered()]


def build_mesh(axes: Optional[Dict[str, int]] = None, devices=None):
    """Build a ``jax.sharding.Mesh`` over ``devices`` (default: all global
    devices).

    With ``axes=None`` the whole device set becomes one data-parallel axis —
    the direct analogue of "N workers" in the reference.  Any one axis may be
    given size -1 to absorb the remaining devices.
    """
    import jax
    from jax.sharding import Mesh
    import numpy as np

    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices)
    n = devices.size

    if not axes:
        return Mesh(devices.reshape(n), ("dp",))

    spec = dict(axes)
    wildcards = [a for a, s in spec.items() if s == -1]
    if len(wildcards) > 1:
        raise ValueError(f"at most one axis may be -1, got {wildcards}")
    if wildcards:
        fixed = math.prod(s for s in spec.values() if s != -1)
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {spec}")
        spec[wildcards[0]] = n // fixed

    ms = MeshSpec(spec)
    if ms.size != n:
        raise ValueError(f"mesh {spec} wants {ms.size} devices, have {n}")
    names = tuple(ms.ordered())
    return Mesh(devices.reshape([spec[a] for a in names]), names)


def mesh_from_jobs(jobs: Sequence, chips_per_task: int = 1) -> MeshSpec:
    """Map the reference's job spec onto mesh axes (north star: ``-w`` →
    data-parallel axis; ``-s`` > 0 enables parameter sharding, i.e. the PS
    role collapses into FSDP).

    Total devices = worker tasks × chips each.  When server/ps tasks exist,
    the mesh gets an ``fsdp`` axis over which parameters shard; its size is
    the full device count (pure FSDP) — matching "PS variables sharded over
    all of ICI" rather than a literal ps count, which has no TPU meaning.
    """
    nworker = sum(j.num - j.start for j in jobs if j.name == "worker")
    nps = sum(j.num - j.start for j in jobs if j.name == "ps")
    if nworker == 0:  # generic jobs: everything data-parallel
        total = sum((j.num - j.start) * max(1, chips_per_task) for j in jobs)
        return MeshSpec({"dp": max(1, total)})
    devices = nworker * max(1, chips_per_task)
    if nps > 0:
        return MeshSpec({"fsdp": devices})
    return MeshSpec({"dp": devices})
