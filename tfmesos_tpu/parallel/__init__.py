from tfmesos_tpu.parallel.mesh import (MeshSpec, build_hybrid_mesh,
                                       build_mesh, mesh_from_jobs)

__all__ = ["MeshSpec", "build_hybrid_mesh", "build_mesh", "mesh_from_jobs"]
