"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context support the reference never had (SURVEY §2.7: no sequence axis
anywhere).  Sequences shard along time over ``sp``; each device computes
blockwise attention of its query block against every key/value block as the
K/V shards rotate around the ring via ``ppermute`` (one ICI hop per step),
with the online-softmax accumulation of flash attention so nothing is ever
materialized at full sequence length.  Memory per device is O(T/sp), compute
overlaps the rotation, and causal masking is exact across shards.

Two inner implementations:

* ``impl="flash"`` (default on TPU) — each ring step runs the Pallas flash
  kernels on the local shard pair and partial outputs merge through their
  logsumexps; a custom VJP re-rotates K/V in the backward and feeds the
  stored GLOBAL lse to the Mosaic dq/dkv kernels, so residual memory stays
  O(T/sp) (plain autodiff of the ring would checkpoint per-step score
  matrices — O(T²/sp)).
* ``impl="xla"`` — the original einsum ring with online softmax; ground
  truth and the CPU path.

Causal structure across shards is the standard ring decomposition: step 0
holds this device's own shard (true causal call); any later step holds a
shard that is either fully visible (owner before us) or fully masked
(owner after us), decided by one scalar — no per-element cross-shard masks.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfmesos_tpu.compat import axis_size, shard_map
from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def ring_attention_local(q, k, v, axis: str = "sp", causal: bool = True,
                         scale: Optional[float] = None,
                         window: Optional[int] = None):
    """The per-device body; call inside ``shard_map`` with ``axis`` in scope.

    Shapes (local): q/k/v ``[B, T/sp, H, D]``.  At ring step ``i`` this
    device holds the K/V shard originally owned by ``(my_index - i) mod sp``,
    so global causal masking only needs the owner index.  A sliding
    ``window`` (causal only) tightens the same global-position mask: the
    owner index gives every held key its global position, so the window
    bound is exact across shards with no extra communication.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sp = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, tq, h, d = q.shape
    tk = k.shape[1]

    qf = q.astype(jnp.float32) * scale
    o = jnp.zeros((b, h, tq, d), jnp.float32)
    m = jnp.full((b, h, tq, 1), float("-inf"), jnp.float32)
    l = jnp.zeros((b, h, tq, 1), jnp.float32)

    qpos = idx * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)

    for step in range(sp):  # static trip count: sp is a mesh constant
        src = (idx - step) % sp  # owner of the K/V shard we hold right now
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
        if causal:
            kpos = src * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
            bad = kpos > qpos
            if window is not None:
                bad = bad | (kpos < qpos - (window - 1))
            s = jnp.where(bad[None, None], float("-inf"), s)
        blockmax = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blockmax)
        # Fully-masked blocks leave m_new at -inf; subtract a finite proxy so
        # exp(-inf - finite) -> 0 instead of exp(-inf - -inf) -> nan.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(m - m_safe)  # m=-inf gives 0: first block overwrites
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
        m = m_new
        if step != sp - 1:
            # Rotate K/V one hop around the ring (device i -> i+1).
            k = ppermute_shift(k, axis, 1)
            v = ppermute_shift(v, axis, 1)

    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l).transpose(0, 2, 1, 3)  # [B, Tq, H, D]
    return out.astype(q.dtype)


def _flash_cfg(q, scale, causal, interpret, window=None, q_offset=0):
    from tfmesos_tpu.ops import attention as A
    t = q.shape[1]
    return A._FlashCfg(causal=causal, scale=scale,
                       block_q=A._pick_block(t), block_k=A._pick_block(t),
                       interpret=bool(interpret), window=window,
                       q_offset=int(q_offset))


def _merge(o_acc, lse_acc, o_i, lse_i):
    """Merge two normalized partial attentions via their logsumexps.

    o: [B, T, H, D]; lse: [B, H, T, 1].  exp(-inf − finite) = 0 handles
    fully-masked partials.
    """
    lse_new = jnp.logaddexp(lse_acc, lse_i)
    w_a = jnp.exp(lse_acc - lse_new).transpose(0, 2, 1, 3)  # [B, T, H, 1]
    w_i = jnp.exp(lse_i - lse_new).transpose(0, 2, 1, 3)
    return o_acc * w_a + o_i * w_i, lse_new


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash(q, k, v, axis, causal, scale, interpret, window):
    return _ring_flash_fwd(q, k, v, axis, causal, scale, interpret,
                           window)[0]


def _step_cfg(q, scale, causal, interpret, window, step):
    """Ring step cfg: with a sliding window every step runs the CAUSAL
    kernel with a static q_offset of step * shard_len — the same
    global-position arithmetic the einsum inner uses, so far-behind
    shards' k-blocks are SKIPPED by the kernel's window bound (O(T·W)
    work across shards, not just within one).  Without a window, steps
    past the first keep the full (causal=False) kernel and mask
    invisible shards wholesale, as before."""
    if window is None:
        return _flash_cfg(q, scale, causal if step == 0 else False,
                          interpret)
    return _flash_cfg(q, scale, True, interpret, window=window,
                      q_offset=step * q.shape[1])


def _ring_flash_fwd(q, k, v, axis, causal, scale, interpret, window):
    from tfmesos_tpu.ops import attention as A
    sp = axis_size(axis)
    idx = jax.lax.axis_index(axis)
    of = jnp.float32

    o, lse = A._flash_forward(
        _step_cfg(q, scale, causal, interpret, window, 0),
        q, k, v)                            # step 0: own shard, causal
    o = o.astype(of)
    kr, vr = k, v
    for step in range(1, sp):
        kr = ppermute_shift(kr, axis, 1)
        vr = ppermute_shift(vr, axis, 1)
        src = (idx - step) % sp  # owner of the shard we now hold
        o_i, lse_i = A._flash_forward(
            _step_cfg(q, scale, causal, interpret, window, step), q, kr,
            vr)
        if causal:
            visible = src < idx  # else: entirely in our future, masked
            lse_i = jnp.where(visible, lse_i, -jnp.inf)
            o_i = jnp.where(visible, o_i.astype(of), 0.0)
        else:
            o_i = o_i.astype(of)
        o, lse = _merge(o, lse, o_i, lse_i)
    out = o.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, causal, scale, interpret, window, res, g):
    """Re-rotate K/V and run the Mosaic backward per shard with the stored
    GLOBAL logsumexp (p = exp(s·scale − lse) is then already normalized over
    the full ring, so per-shard contributions just sum).  dk/dv accumulators
    ride the ring with their shards; after sp total hops every contribution
    is back on its owner."""
    from tfmesos_tpu.ops import attention as A
    q, k, v, out, lse = res
    sp = axis_size(axis)
    idx = jax.lax.axis_index(axis)

    dq, dk, dv = A._mha_bwd_pallas(
        _step_cfg(q, scale, causal, interpret, window, 0), q, k, v, out,
        lse, g, out_dtype=jnp.float32)
    kr, vr = k, v
    for step in range(1, sp):
        kr = ppermute_shift(kr, axis, 1)
        vr = ppermute_shift(vr, axis, 1)
        dk = ppermute_shift(dk, axis, 1)
        dv = ppermute_shift(dv, axis, 1)
        src = (idx - step) % sp
        dqc, dkc, dvc = A._mha_bwd_pallas(
            _step_cfg(q, scale, causal, interpret, window, step), q, kr,
            vr, out, lse, g, out_dtype=jnp.float32)
        if causal:
            visible = (src < idx).astype(jnp.float32)
            dqc = dqc * visible
            dkc = dkc * visible
            dvc = dvc * visible
        dq = dq + dqc
        dk = dk + dkc
        dv = dv + dvc
    # One final hop completes the full ring: contributions land home.
    dk = ppermute_shift(dk, axis, 1)
    dv = ppermute_shift(dv, axis, 1)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None, impl: Optional[str] = None,
                   interpret: bool = False, window: Optional[int] = None):
    """Sharded entry point: q/k/v are global ``[B, T, H, D]`` arrays (or
    tracers under jit) with T sharded over ``axis``.

    Falls back to single-device flash/reference attention when the mesh has
    no (non-trivial) ``axis`` — so model code calls this unconditionally.
    ``impl=None`` auto-selects: Pallas-inner ring on TPU (or when
    ``interpret``), the einsum ring elsewhere.

    ``window`` (causal only): sliding-window attention, exact across
    shards — the owner-index arithmetic that bounds causal visibility
    also bounds the window, per step.  Both inners support it: the
    Pallas ring runs every step's kernels with a static ``q_offset`` of
    step x shard_len (the offset-window form), whose block bounds SKIP
    k-blocks outside the window — O(T·W) work across the whole ring —
    while the einsum inner masks by global position.
    """
    if impl not in (None, "flash", "xla"):
        raise ValueError(f"impl must be None, 'flash', or 'xla'; got {impl!r}")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    if axis not in mesh.shape or mesh.shape[axis] == 1:
        # Trivial-axis fallback: an ordinary single-device call (the
        # kernel's q/k blocks share one global origin: q_offset = 0).
        from tfmesos_tpu.ops.attention import flash_attention
        use_pallas = {None: None, "flash": True, "xla": False}[impl]
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               interpret=interpret, use_pallas=use_pallas,
                               window=window)
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    local_t = q.shape[1] // mesh.shape[axis]
    if impl is None:
        on_tpu = jax.default_backend() == "tpu"
        impl = "flash" if (on_tpu or interpret) and local_t % 8 == 0 else "xla"
    elif impl == "flash":
        from tfmesos_tpu.ops.attention import _pick_block
        if _pick_block(local_t) > 1024:
            # Mirror flash_attention's forced-pallas guard: fail fast with
            # a clear error instead of an opaque Mosaic lowering failure.
            raise ValueError(
                f"ring_attention(impl='flash'): local shard length "
                f"{local_t} has no Mosaic-legal block tiling")
    spec = P(data_axes(mesh), axis, None, None)
    if impl == "flash":
        body = lambda q_, k_, v_: _ring_flash(
            q_, k_, v_, axis, bool(causal), float(scale), bool(interpret),
            None if window is None else int(window))
    else:
        body = lambda q_, k_, v_: ring_attention_local(
            q_, k_, v_, axis=axis, causal=causal, scale=scale, window=window)
    fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    return fn(q, k, v)
