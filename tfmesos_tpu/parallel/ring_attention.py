"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

Long-context support the reference never had (SURVEY §2.7: no sequence axis
anywhere).  Sequences shard along time over ``sp``; each device computes
blockwise attention of its query block against every key/value block as the
K/V shards rotate around the ring via ``ppermute`` (one ICI hop per step),
with the online-softmax accumulation of flash attention so nothing is ever
materialized at full sequence length.  Memory per device is O(T/sp), compute
overlaps the rotation, and causal masking is exact across shards.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfmesos_tpu.parallel.collectives import ppermute_shift
from tfmesos_tpu.parallel.sharding import data_axes


def ring_attention_local(q, k, v, axis: str = "sp", causal: bool = True,
                         scale: Optional[float] = None):
    """The per-device body; call inside ``shard_map`` with ``axis`` in scope.

    Shapes (local): q/k/v ``[B, T/sp, H, D]``.  At ring step ``i`` this
    device holds the K/V shard originally owned by ``(my_index - i) mod sp``,
    so global causal masking only needs the owner index.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sp = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    b, tq, h, d = q.shape
    tk = k.shape[1]

    qf = q.astype(jnp.float32) * scale
    o = jnp.zeros((b, h, tq, d), jnp.float32)
    m = jnp.full((b, h, tq, 1), float("-inf"), jnp.float32)
    l = jnp.zeros((b, h, tq, 1), jnp.float32)

    qpos = idx * tq + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)

    for step in range(sp):  # static trip count: sp is a mesh constant
        src = (idx - step) % sp  # owner of the K/V shard we hold right now
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k.astype(jnp.float32))
        if causal:
            kpos = src * tk + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
            s = jnp.where((kpos > qpos)[None, None], float("-inf"), s)
        blockmax = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blockmax)
        # Fully-masked blocks leave m_new at -inf; subtract a finite proxy so
        # exp(-inf - finite) -> 0 instead of exp(-inf - -inf) -> nan.
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(s - m_safe)
        corr = jnp.exp(m - m_safe)  # m=-inf gives 0: first block overwrites
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        o = o * corr + jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
        m = m_new
        if step != sp - 1:
            # Rotate K/V one hop around the ring (device i -> i+1).
            k = ppermute_shift(k, axis, 1)
            v = ppermute_shift(v, axis, 1)

    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l).transpose(0, 2, 1, 3)  # [B, Tq, H, D]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal: bool = True,
                   scale: Optional[float] = None):
    """Sharded entry point: q/k/v are global ``[B, T, H, D]`` arrays (or
    tracers under jit) with T sharded over ``axis``.

    Falls back to single-device flash/reference attention when the mesh has
    no (non-trivial) ``axis`` — so model code calls this unconditionally.
    """
    if axis not in mesh.shape or mesh.shape[axis] == 1:
        from tfmesos_tpu.ops.attention import flash_attention
        return flash_attention(q, k, v, causal=causal, scale=scale)
    spec = P(data_axes(mesh), axis, None, None)
    fn = jax.shard_map(
        lambda q_, k_, v_: ring_attention_local(q_, k_, v_, axis=axis,
                                                causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)
