"""Parameter/batch sharding rules: PartitionSpec trees for a param pytree.

The reference shards variables over parameter servers with
``replica_device_setter`` round-robin (mnist.py:43, mnist_replica.py:116-119)
and by hand with ``tf.device('/job:ps/task:k')`` (matrix_factorization.py:
21-28).  The GSPMD equivalent is a PartitionSpec per parameter: FSDP shards
each tensor's largest divisible axis over the ``fsdp`` mesh axis, and logical
rules map named parameter axes onto ``tp``/``ep`` style mesh axes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def data_axes(mesh: Mesh):
    """The mesh axes batch-like dims shard over (the single source of truth
    for 'what counts as a data axis' — attention and batch specs share it)."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.shape
                 and mesh.shape[a] > 1) or None


def fsdp_spec(shape: Sequence[int], mesh: Mesh, axis: str = "fsdp",
              min_size: int = 1024) -> P:
    """FSDP rule for one tensor: shard the largest dimension divisible by the
    axis size; leave small tensors replicated (sharding a 100-element bias
    buys nothing and costs an all-gather)."""
    if axis not in mesh.shape:
        return P()
    n = mesh.shape[axis]
    if n == 1 or int(np.prod(shape or [1])) < min_size:
        return P()
    dims = sorted(range(len(shape)), key=lambda d: shape[d], reverse=True)
    for d in dims:
        if shape[d] % n == 0:
            spec = [None] * len(shape)
            spec[d] = axis
            return P(*spec)
    return P()


def fsdp_sharding_tree(params: Any, mesh: Mesh, axis: str = "fsdp",
                       min_size: int = 1024) -> Any:
    """NamedSharding tree matching a parameter pytree."""
    return jax.tree_util.tree_map(
        lambda p: NamedSharding(mesh, fsdp_spec(p.shape, mesh, axis, min_size)),
        params)


def batch_spec(mesh: Mesh, *, extra_dims: int = 0) -> P:
    """Batch sharding: leading dim over every data-like axis present
    (``dp`` and/or ``fsdp``), optional sequence dim over ``sp``."""
    dims = [data_axes(mesh)]
    if extra_dims >= 1 and "sp" in mesh.shape and mesh.shape["sp"] > 1:
        dims.append("sp")
        extra_dims -= 1
    dims.extend([None] * extra_dims)
    return P(*dims)


def batch_sharding(mesh: Mesh, *, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, extra_dims=extra_dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def place_tree(mesh: Mesh, tree: Any, sharding_tree: Optional[Any] = None) -> Any:
    """Place a host-local pytree as global arrays under the given shardings
    (default: fully replicated).

    Works in multi-controller runs where every process holds identical full
    values (e.g. params from a shared PRNG seed): each process contributes
    its addressable shards via ``make_array_from_callback`` slicing its own
    copy, so both replicated and sharded placements assemble correctly.
    """
    import jax

    if sharding_tree is None:
        rep = NamedSharding(mesh, P())
        sharding_tree = jax.tree_util.tree_map(lambda _: rep, tree)

    def put(x, sh):
        x = np.asarray(x)
        return jax.make_array_from_callback(x.shape, sh,
                                            lambda idx: x[idx])

    return jax.tree_util.tree_map(put, tree, sharding_tree)


def replicate_tree(mesh: Mesh, tree: Any) -> Any:
    """Mesh-replicated placement of a host-local pytree."""
    return place_tree(mesh, tree)


def make_global_batch(mesh: Mesh, batch: Dict[str, Any],
                      replicate: bool = False,
                      batch_dim: int = 0) -> Dict[str, Any]:
    """Assemble per-process host-local numpy arrays into global jax.Arrays.

    In multi-controller JAX a jit over a multi-host mesh requires global
    arrays — each process contributes its local shard (its slice of the
    global batch) and the result's leading dim is the sum across processes.
    With ``replicate=True`` every process must hold identical data (e.g. an
    eval batch built from a shared seed).  Single-process: a cheap no-op
    placement either way.
    """
    import jax

    out = {}
    for name, v in batch.items():
        if replicate or v.ndim <= batch_dim:
            spec = P()  # scalars / low-rank leaves replicate
        else:
            # ``batch_dim`` selects which dim shards over the data axes
            # (e.g. 1 for [steps_per_call, B, ...] stacked batches).
            dims = [None] * v.ndim
            dims[batch_dim] = data_axes(mesh)
            spec = P(*dims)
        sharding = NamedSharding(mesh, spec)
        out[name] = jax.make_array_from_process_local_data(sharding, v)
    return out


def apply_rules(path_specs: Dict[str, P], params: Any, mesh: Mesh,
                default: Optional[P] = None) -> Any:
    """Map dotted-path substring rules onto a param pytree.

    ``path_specs`` maps a substring of the flattened parameter path (e.g.
    ``"attn.wq"``) to a PartitionSpec; first match wins, ``default`` (or
    replication) otherwise.  This is the manual-placement successor of
    ``tf.device('/job:ps/task:k')`` pins.
    """
    flat = jax.tree_util.tree_flatten_with_path(params)
    leaves, treedef = flat

    def spec_for(path) -> P:
        name = ".".join(str(getattr(k, "key", getattr(k, "idx", k)))
                        for k in path)
        for pattern, spec in path_specs.items():
            if pattern in name:
                return spec
        return default if default is not None else P()

    shardings = [NamedSharding(mesh, spec_for(path)) for path, _ in leaves]
    return jax.tree_util.tree_unflatten(treedef, shardings)
