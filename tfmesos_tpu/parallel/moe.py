"""Expert parallelism: switch/top-k MoE with a real all_to_all data path.

The flagship transformer's default MoE computes every expert densely and
masks (models/transformer.py:_moe) — exact but O(E) FLOPs.  This module is
the scalable path: top-k routing (k=1 switch-style by default) with a
capacity limit, experts sharded over the ``ep`` mesh axis, and tokens
physically exchanged with two ``lax.all_to_all`` hops (dispatch to expert
owners, combine back) so each device computes only its own experts.  This is
the standard TPU MoE layout: the all_to_alls ride ICI and the per-expert
matmuls stay dense and MXU-shaped ``[capacity, d] x [d, f]``.

Semantics (shared by the naive reference and the sharded path, so they are
bit-comparable in tests): each token takes its top-k experts; an assignment
lands if it arrives within the expert's capacity, with slot priority by
choice rank then batch order (all first choices beat any second choice);
kept assignments are weighted by the router probability (renormalized over
the top-k when k > 1, raw switch-style when k == 1); dropped assignments
contribute zero (the residual stream carries the token).

Router health is surfaced rather than assumed: ``return_aux=True`` yields
the standard auxiliary load-balance loss (E·Σ_e f_e·P_e — 1.0 at perfect
balance), the router z-loss (mean log²-sum-exp, which keeps logits from
drifting into saturation), and the realized token-overflow fraction.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfmesos_tpu.compat import axis_size, shard_map


def _routing(x, router_w, n_experts: int, capacity: int, top_k: int = 1
             ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Shared routing math.

    Returns ``combine`` [n, E, C] — fp32 gate weight of each kept
    (token, expert, slot) assignment (the dispatch mask is ``combine > 0``)
    — and the aux metrics dict.
    """
    logits = (x @ router_w).astype(jnp.float32)              # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, top_k)               # [n, k]
    if top_k == 1:
        gates = top_p                                        # switch: raw prob
    else:
        gates = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    onehot = jax.nn.one_hot(top_e, n_experts, dtype=jnp.float32)  # [n, k, E]
    # Slot assignment with choice priority: cumsum in choice-major order so
    # every token's first choice outranks any token's second choice.
    n = x.shape[0]
    flat = onehot.transpose(1, 0, 2).reshape(top_k * n, n_experts)
    pos_flat = jnp.cumsum(flat, axis=0) * flat - 1.0
    pos = pos_flat.reshape(top_k, n, n_experts).transpose(1, 0, 2)  # [n,k,E]
    keep = (pos >= 0.0) & (pos < capacity)
    slot = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    combine = jnp.sum(
        onehot[..., None]
        * jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        * keep[..., None].astype(jnp.float32)
        * gates[..., None, None],
        axis=1)                                              # [n, E, C]

    # Aux stats over the PRE-capacity assignment (the load balance you want
    # to fix is visible before the capacity limit starts dropping tokens).
    f = jnp.sum(onehot, axis=(0, 1)) / (n * top_k)           # assignment frac
    p_mean = jnp.mean(probs, axis=0)                         # mean router prob
    aux = {
        "load_balance_loss": n_experts * jnp.sum(f * p_mean),
        "z_loss": jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2),
        "overflow_frac": 1.0 - jnp.sum(keep) / (n * top_k),
    }
    return combine, aux


def _expert_ffn(tokens, w_gate, w_up, w_down, compute_dtype):
    """Per-expert SwiGLU over [E_loc, C', d] token blocks."""
    t = tokens.astype(compute_dtype)
    g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", t, w_gate.astype(compute_dtype)))
    u = jnp.einsum("ecd,edf->ecf", t, w_up.astype(compute_dtype))
    return jnp.einsum("ecf,efd->ecd", g * u, w_down.astype(compute_dtype))


def _capacity(n_tokens: int, n_experts: int, factor: float,
              top_k: int = 1) -> int:
    return max(1, math.ceil(n_tokens * top_k * factor / n_experts))


def switch_moe_reference(x, router_w, w_gate, w_up, w_down,
                         capacity_factor: float = 1.25, top_k: int = 1,
                         return_aux: bool = False):
    """Naive single-device top-k MoE (ground truth for the sharded path).

    x: [n, d]; router_w: [d, E]; w_gate/w_up: [E, d, f]; w_down: [E, f, d].
    """
    n, d = x.shape
    e = router_w.shape[-1]
    capacity = _capacity(n, e, capacity_factor, top_k)
    combine, aux = _routing(x, router_w, e, capacity, top_k)
    dispatch = (combine > 0.0).astype(jnp.float32)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
    expert_out = _expert_ffn(expert_in, w_gate, w_up, w_down, x.dtype)
    out = jnp.einsum("nec,ecd->nd", combine,
                     expert_out.astype(jnp.float32)).astype(x.dtype)
    return (out, aux) if return_aux else out


def switch_moe_local(x, router_w, w_gate, w_up, w_down, axis: str = "ep",
                     capacity_factor: float = 1.25, top_k: int = 1):
    """Per-device body (call inside shard_map): tokens local [n_loc, d],
    experts local [E/ep, d, f]; two all_to_all hops move token blocks to
    their expert owners and back.  Returns (out, aux) with aux scalars
    averaged over the ``axis`` group (callers pmean the data axes)."""
    ep = axis_size(axis)
    n_loc, d = x.shape
    e_loc = w_gate.shape[0]
    e = e_loc * ep
    capacity = _capacity(n_loc, e, capacity_factor, top_k)

    combine, aux = _routing(x, router_w, e, capacity, top_k)  # [n, E, C]
    dispatch = (combine > 0.0).astype(jnp.float32)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(jnp.float32))            # [E, C, d]

    # Hop 1: split the expert dim across the ring; device p receives, from
    # every peer, the token blocks destined for ITS experts.  tiled=True
    # keeps ranks stable (shape[split] /= ep, shape[concat] *= ep) and has a
    # well-defined transpose for the backward pass.
    blocks = expert_in.reshape(ep, e_loc, capacity, d)
    received = jax.lax.all_to_all(blocks, axis, split_axis=0, concat_axis=2,
                                  tiled=True)
    # received: [1, e_loc, ep*C, d], capacity axis grouped by source device.
    received = received.reshape(e_loc, ep * capacity, d)

    out = _expert_ffn(received, w_gate, w_up, w_down, x.dtype)  # [e_loc, ep*C, d]

    # Hop 2: send each source device its processed block back.
    out = out.astype(jnp.float32).reshape(e_loc, ep, capacity, d)
    out = jnp.moveaxis(out, 1, 0)                            # [ep, e_loc, C, d]
    returned = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    # returned: [ep, e_loc, C, d] indexed by expert-owner rank — i.e.
    # [E, C, d] in global expert order for my local tokens.
    returned = returned.reshape(e, capacity, d)

    combined = jnp.einsum("nec,ecd->nd", combine, returned)
    aux = {k: jax.lax.pmean(v, axis) for k, v in aux.items()}
    return combined.astype(x.dtype), aux


def switch_moe_replicated_local(x, router_w, w_gate, w_up, w_down,
                                ep_axis: str = None,
                                capacity_factor: float = 1.25,
                                top_k: int = 1, tp_axis: str = None):
    """Capacity MoE for ep-REPLICATED tokens (the pipeline-stage layout).

    Inside ``pipeline_apply`` activations replicate over ``ep`` while the
    expert weights shard over it, so no all_to_all is needed: every device
    already holds every token, computes the capacity slots of its LOCAL
    experts only, and the partial outputs ``psum`` over ``ep``.  Same
    routing semantics as ``switch_moe_local`` (slot priority, capacity
    drops, gate weighting); the router weight must be replicated so every
    device sees the full [n, E] logits.  ``ep_axis=None`` runs all experts
    locally (pp without ep).  ``tp_axis`` additionally shards every
    expert's FFN width (w_gate/w_up [e_loc, d, f/tp], w_down
    [e_loc, f/tp, d]) — the w_down contraction yields a partial sum, so
    one psum covers both axes.  Returns (out, aux); aux is identical
    across the ep/tp groups by construction.
    """
    if not ep_axis and not tp_axis:
        return switch_moe_reference(x, router_w, w_gate, w_up, w_down,
                                    capacity_factor, top_k=top_k,
                                    return_aux=True)
    n, d = x.shape
    e_loc = w_gate.shape[0]
    e = e_loc * (axis_size(ep_axis) if ep_axis else 1)
    capacity = _capacity(n, e, capacity_factor, top_k)
    combine, aux = _routing(x, router_w, e, capacity, top_k)  # [n, E, C]
    if ep_axis:
        idx = jax.lax.axis_index(ep_axis)
        combine = jax.lax.dynamic_slice_in_dim(combine, idx * e_loc, e_loc,
                                               axis=1)       # [n, e_loc, C]
    dispatch = (combine > 0.0).astype(jnp.float32)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
    expert_out = _expert_ffn(expert_in, w_gate, w_up, w_down, x.dtype)
    out = jnp.einsum("nec,ecd->nd", combine, expert_out.astype(jnp.float32))
    psum_axes = tuple(a for a in (ep_axis, tp_axis) if a)
    return jax.lax.psum(out, psum_axes).astype(x.dtype), aux


def switch_moe(x, router_w, w_gate, w_up, w_down, mesh: Mesh,
               axis: str = "ep", capacity_factor: float = 1.25,
               top_k: int = 1, return_aux: bool = False):
    """Sharded entry point: x [n, d] sharded over the data axes, experts
    sharded over ``axis``.  Falls back to the reference when the mesh has no
    (non-trivial) ``axis``."""
    if axis not in mesh.shape or mesh.shape[axis] == 1:
        return switch_moe_reference(x, router_w, w_gate, w_up, w_down,
                                    capacity_factor, top_k=top_k,
                                    return_aux=return_aux)
    from tfmesos_tpu.parallel.sharding import data_axes
    batch = data_axes(mesh)
    dspec = P(batch, None)
    espec = P(axis, None, None)
    batch_names = (tuple(a for a in (batch if isinstance(batch, tuple)
                                     else (batch,)) if a)
                   if batch is not None else ())

    def body(x_, r_, g_, u_, dn_):
        out, aux = switch_moe_local(x_, r_, g_, u_, dn_, axis=axis,
                                    capacity_factor=capacity_factor,
                                    top_k=top_k)
        if batch_names:
            aux = {k: jax.lax.pmean(v, batch_names) for k, v in aux.items()}
        return out, aux

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(dspec, P(None, None), espec, espec, espec),
        out_specs=(dspec, {k: P() for k in ("load_balance_loss", "z_loss",
                                            "overflow_frac")}),
        check_vma=False)
    out, aux = fn(x, router_w, w_gate, w_up, w_down)
    return (out, aux) if return_aux else out
