"""Expert parallelism: switch-style MoE with a real all_to_all data path.

The flagship transformer's default MoE computes every expert densely and
masks (models/transformer.py:_moe) — exact but O(E) FLOPs.  This module is
the scalable path: top-1 (switch) routing with a capacity limit, experts
sharded over the ``ep`` mesh axis, and tokens physically exchanged with two
``lax.all_to_all`` hops (dispatch to expert owners, combine back) so each
device computes only its own experts.  This is the standard TPU MoE layout:
the all_to_alls ride ICI and the per-expert matmuls stay dense and
MXU-shaped ``[capacity, d] x [d, f]``.

Semantics (shared by the naive reference and the sharded path, so they are
bit-comparable in tests): token i goes to its argmax expert if it arrives
within the expert's capacity (position by order within the batch), weighted
by the router's softmax probability; overflow tokens pass through with a
zero MoE contribution (the residual stream carries them).
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _routing(x, router_w, n_experts: int, capacity: int):
    """Shared routing math: returns (dispatch [n, E, C], gates [n])."""
    logits = (x @ router_w).astype(jnp.float32)              # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [n]
    gate = jnp.max(probs, axis=-1)                           # [n]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [n, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # slot per token
    keep = (pos >= 0) & (pos < capacity)
    dispatch = onehot[..., None] * jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity,
        dtype=jnp.float32) * keep[..., None].astype(jnp.float32)  # [n, E, C]
    return dispatch, gate


def _expert_ffn(tokens, w_gate, w_up, w_down, compute_dtype):
    """Per-expert SwiGLU over [E_loc, C', d] token blocks."""
    t = tokens.astype(compute_dtype)
    g = jax.nn.silu(jnp.einsum("ecd,edf->ecf", t, w_gate.astype(compute_dtype)))
    u = jnp.einsum("ecd,edf->ecf", t, w_up.astype(compute_dtype))
    return jnp.einsum("ecf,efd->ecd", g * u, w_down.astype(compute_dtype))


def switch_moe_reference(x, router_w, w_gate, w_up, w_down,
                         capacity_factor: float = 1.25):
    """Naive single-device switch MoE (ground truth for the sharded path).

    x: [n, d]; router_w: [d, E]; w_gate/w_up: [E, d, f]; w_down: [E, f, d].
    """
    n, d = x.shape
    e = router_w.shape[-1]
    capacity = _capacity(n, e, capacity_factor)
    dispatch, gate = _routing(x, router_w, e, capacity)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x.astype(jnp.float32))
    expert_out = _expert_ffn(expert_in, w_gate, w_up, w_down, x.dtype)
    combined = jnp.einsum("nec,ecd->nd", dispatch,
                          expert_out.astype(jnp.float32))
    return (combined * gate[:, None]).astype(x.dtype)


def _capacity(n_tokens: int, n_experts: int, factor: float) -> int:
    return max(1, math.ceil(n_tokens * factor / n_experts))


def switch_moe_local(x, router_w, w_gate, w_up, w_down, axis: str = "ep",
                     capacity_factor: float = 1.25):
    """Per-device body (call inside shard_map): tokens local [n_loc, d],
    experts local [E/ep, d, f]; two all_to_all hops move token blocks to
    their expert owners and back."""
    ep = jax.lax.axis_size(axis)
    n_loc, d = x.shape
    e_loc = w_gate.shape[0]
    e = e_loc * ep
    capacity = _capacity(n_loc, e, capacity_factor)

    dispatch, gate = _routing(x, router_w, e, capacity)      # [n, E, C]
    expert_in = jnp.einsum("nec,nd->ecd", dispatch,
                           x.astype(jnp.float32))            # [E, C, d]

    # Hop 1: split the expert dim across the ring; device p receives, from
    # every peer, the token blocks destined for ITS experts.  tiled=True
    # keeps ranks stable (shape[split] /= ep, shape[concat] *= ep) and has a
    # well-defined transpose for the backward pass.
    blocks = expert_in.reshape(ep, e_loc, capacity, d)
    received = jax.lax.all_to_all(blocks, axis, split_axis=0, concat_axis=2,
                                  tiled=True)
    # received: [1, e_loc, ep*C, d], capacity axis grouped by source device.
    received = received.reshape(e_loc, ep * capacity, d)

    out = _expert_ffn(received, w_gate, w_up, w_down, x.dtype)  # [e_loc, ep*C, d]

    # Hop 2: send each source device its processed block back.
    out = out.astype(jnp.float32).reshape(e_loc, ep, capacity, d)
    out = jnp.moveaxis(out, 1, 0)                            # [ep, e_loc, C, d]
    returned = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    # returned: [ep, e_loc, C, d] indexed by expert-owner rank — i.e.
    # [E, C, d] in global expert order for my local tokens.
    returned = returned.reshape(e, capacity, d)

    combined = jnp.einsum("nec,ecd->nd", dispatch, returned)
    return (combined * gate[:, None]).astype(x.dtype)


def switch_moe(x, router_w, w_gate, w_up, w_down, mesh: Mesh,
               axis: str = "ep", capacity_factor: float = 1.25):
    """Sharded entry point: x [n, d] sharded over the data axes, experts
    sharded over ``axis``.  Falls back to the reference when the mesh has no
    (non-trivial) ``axis``."""
    if axis not in mesh.shape or mesh.shape[axis] == 1:
        return switch_moe_reference(x, router_w, w_gate, w_up, w_down,
                                    capacity_factor)
    from tfmesos_tpu.parallel.sharding import data_axes
    dspec = P(data_axes(mesh), None)
    espec = P(axis, None, None)
    fn = jax.shard_map(
        lambda x_, r_, g_, u_, dn_: switch_moe_local(
            x_, r_, g_, u_, dn_, axis=axis, capacity_factor=capacity_factor),
        mesh=mesh,
        in_specs=(dspec, P(None, None), espec, espec, espec),
        out_specs=dspec, check_vma=False)
    return fn(x, router_w, w_gate, w_up, w_down)
