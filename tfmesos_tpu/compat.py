"""Version compatibility shims over the jax API surface.

One import site per symbol: modules that need ``shard_map`` import it
from here instead of feeling out the jax version themselves.

``shard_map`` graduated from ``jax.experimental.shard_map`` to
``jax.shard_map`` (and its replication-check keyword was renamed
``check_rep`` -> ``check_vma``) across the 0.4.x -> 0.6 line.  The
codebase is written against the NEW surface (``jax.shard_map`` with
``check_vma=``); on a 0.4.x jax this adapter maps the call onto the
experimental entry point so every mesh/shard_map path traces instead of
dying with ``AttributeError: module 'jax' has no attribute
'shard_map'``.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "axis_size"]

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax 0.4.x: experimental entry point, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:  # jax 0.4.x: axis sizes live on the core axis env (static ints)
    def axis_size(axis):
        if isinstance(axis, (tuple, list)):
            n = 1
            for a in axis:
                n *= jax.core.axis_frame(a)
            return n
        return jax.core.axis_frame(axis)
