"""Fleet autoscaler + blue-green rollout (tfmesos_tpu/fleet/autoscaler.py,
FleetServer.rollout): jax-free control-loop units over a fake fleet with
injected signals and a fake clock (the chaos.py determinism discipline),
a stub-replica smoke exercising the full scale-up → warming → routable →
drain-by-node-id → kill path without a model, dynamic-scheduler units on
LocalBackend, and the end-to-end acceptance paths: a signal surge grows
a real CPU fleet through the warming state with zero failed requests,
and a rollout to a new weights_version completes with every request
served, the router never selecting the old version after the shift, and
old-generation stragglers fenced out of re-registration."""

import threading
import time

import numpy as np
import pytest

from tfmesos_tpu import wire
from tfmesos_tpu.fleet.admission import AdmissionController
from tfmesos_tpu.fleet.autoscaler import AutoscalerConfig, FleetAutoscaler
from tfmesos_tpu.fleet.client import FleetClient, RequestFailed
from tfmesos_tpu.fleet.gateway import Gateway
from tfmesos_tpu.fleet.metrics import FleetMetrics, Histogram
from tfmesos_tpu.fleet.registry import (ALIVE, DEAD, DRAINING, WARMING,
                                        ReplicaInfo, ReplicaRegistry)
from tfmesos_tpu.fleet.replica import ReplicaServer
from tfmesos_tpu.fleet.router import Router


def _wait(cond, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


SURGE = {"queue_wait_p99_ms": 5000.0, "util": 1.0, "kv_headroom": None}
CALM = {"queue_wait_p99_ms": 0.0, "util": 0.0, "kv_headroom": None}
MID = {"queue_wait_p99_ms": 200.0, "util": 0.5, "kv_headroom": None}


# -- fakes (no sockets, no model) -------------------------------------------


class FakeRegistry:
    """Just enough registry surface for the control loop."""

    def __init__(self, reps=()):
        self.reps = list(reps)
        self.drained = []
        self.targets = {}

    def members(self, role=None):
        return [r for r in self.reps
                if role is None or (r.role or "unified") == role]

    def role_summary(self):
        out = {}
        for r in self.reps:
            d = out.setdefault(r.role or "unified",
                               {"alive": 0, "warming": 0, "draining": 0,
                                "dead": 0, "outstanding": 0,
                                "kv_headroom": 0, "versions": {}})
            d[r.state] = d.get(r.state, 0) + 1
            if r.state == ALIVE:
                d["outstanding"] += r.outstanding
                if r.kv_headroom > 0:
                    d["kv_headroom"] += r.kv_headroom
        return out

    def set_target(self, role, n):
        self.targets[role] = n

    def begin_drain(self, addr, pinned=True):
        for r in self.reps:
            if r.addr == addr:
                r.state = DRAINING
                self.drained.append(addr)
                return True
        return False

    def clear_drain(self, addr):
        self.drained = [a for a in self.drained if a != addr]
        for r in self.reps:
            if r.addr == addr:
                r.state = ALIVE


class FakeFleet:
    """The launch/kill surface the autoscaler actuates against."""

    def __init__(self, registry, targets, bounds=(1, 4)):
        self.registry = registry
        self.metrics = FleetMetrics()
        self.targets = dict(targets)
        self._bounds = tuple(bounds)
        self.scale_lock = threading.RLock()
        self.launched = []
        self.killed = []
        self.dead_nodes = set()     # tasks already gone from the table
        self._actual = dict(targets)

    def set_target(self, role, n):
        self.targets[role] = n
        self.registry.set_target(role, n)

    def bounds(self, role):
        return self._bounds

    def launch_replica(self, role, weights_version=None):
        node = f"{role}:{len(self.launched)}"
        self.launched.append((role, node))
        self._actual[role] = self._actual.get(role, 0) + 1
        return node

    def kill_replica(self, node):
        if node in self.dead_nodes:
            return False            # remove_task on a vanished task
        self.killed.append(node)
        role = node.split(":", 1)[0]
        self._actual[role] = self._actual.get(role, 1) - 1
        return True

    def tier_actual(self, role):
        return self._actual.get(role, 0)


def _rep(addr, role="unified", state=ALIVE, outstanding=0, node="",
         capacity=4, weights_version=""):
    return ReplicaInfo(addr=addr, role=role, state=state,
                       outstanding=outstanding, node=node,
                       capacity=capacity, weights_version=weights_version)


def _auto(fleet, sig, clock, **cfg):
    config = AutoscalerConfig(**cfg)
    return FleetAutoscaler(fleet, config,
                           signals=lambda: {k: dict(v)
                                            for k, v in sig.items()},
                           clock=lambda: clock[0])


# -- control-loop units -----------------------------------------------------


def test_autoscaler_surge_scales_up_with_cooldown_and_hysteresis():
    reg = FakeRegistry([_rep("a:1")])
    fleet = FakeFleet(reg, {"unified": 1}, bounds=(1, 4))
    sig = {"unified": dict(SURGE)}
    clock = [0.0]
    auto = _auto(fleet, sig, clock, scale_up_cooldown=5.0,
                 scale_down_cooldown=30.0)
    auto.step()                     # surge: target 1 -> 2, one launch
    assert fleet.targets["unified"] == 2
    assert [r for r, _ in fleet.launched] == ["unified"]
    auto.step()                     # same instant: up-cooldown holds it
    assert fleet.targets["unified"] == 2
    assert len(fleet.launched) == 1     # converged; no duplicate launch
    clock[0] = 10.0
    auto.step()                     # cooldown over, surge persists: -> 3
    assert fleet.targets["unified"] == 3
    assert len(fleet.launched) == 2
    # Hysteresis dead band: a mid-band signal changes NOTHING even with
    # every cooldown expired — the up and down thresholds never touch.
    sig["unified"] = dict(MID)
    clock[0] = 1000.0
    auto.step()
    assert fleet.targets["unified"] == 3
    assert fleet.metrics.get("autoscale_up") == 2
    assert fleet.metrics.get("autoscale_down") == 0


def test_autoscaler_calm_drains_least_loaded_then_kills_after_flush():
    busy = _rep("a:1", outstanding=3, node="replica:0")
    idle = _rep("a:2", outstanding=0, node="replica:1")
    reg = FakeRegistry([busy, idle])
    fleet = FakeFleet(reg, {"unified": 2})
    sig = {"unified": dict(CALM)}
    clock = [100.0]
    auto = _auto(fleet, sig, clock, scale_down_cooldown=0.0,
                 drain_grace=1.0, drain_timeout=60.0)
    auto.step()             # target 2 -> 1; drain the LEAST-loaded
    assert fleet.targets["unified"] == 1
    assert reg.drained == ["a:2"]
    assert idle.state == DRAINING
    assert not fleet.killed             # grace: outstanding may lag
    clock[0] = 100.5
    auto.step()                         # still inside the grace window
    assert not fleet.killed
    # In-flight work appears on a beat: the kill must wait for flush.
    idle.outstanding = 2
    clock[0] = 105.0
    auto.step()
    assert not fleet.killed
    idle.outstanding = 0
    clock[0] = 110.0
    auto.step()                         # flushed + grace passed: reap
    assert fleet.killed == ["replica:1"]
    assert fleet.metrics.get("autoscale_kills") == 1
    # No further drain: actual converged to target.
    auto.step()
    assert reg.drained == ["a:2"]


def test_autoscaler_drain_timeout_reaps_a_stuck_victim():
    stuck = _rep("a:1", outstanding=9, node="replica:0")
    reg = FakeRegistry([stuck, _rep("a:2", outstanding=0,
                                    node="replica:1")])
    fleet = FakeFleet(reg, {"unified": 2})
    sig = {"unified": dict(CALM)}
    clock = [0.0]
    auto = _auto(fleet, sig, clock, scale_down_cooldown=0.0,
                 drain_grace=0.5, drain_timeout=30.0)
    auto.step()
    assert reg.drained == ["a:2"]
    # The victim never flushes (its beats keep reporting outstanding):
    victim = reg.reps[1]
    victim.outstanding = 7
    clock[0] = 29.0
    auto.step()
    assert not fleet.killed
    clock[0] = 31.0
    auto.step()                         # deadline passed: kill anyway
    assert fleet.killed == ["replica:1"]


def test_autoscaler_victim_death_mid_drain_does_not_spur_a_launch():
    """A draining victim that dies before its reap already left the
    scheduler table: its drain record must not ALSO discount actual, or
    the loop would launch a spurious replica (full warmup churn) and
    then drain it right back."""
    a = _rep("a:1", outstanding=0, node="replica:0")
    b = _rep("a:2", outstanding=1, node="replica:1")
    reg = FakeRegistry([a, b])
    fleet = FakeFleet(reg, {"unified": 2})
    clock = [0.0]
    auto = _auto(fleet, {"unified": dict(CALM)}, clock,
                 scale_down_cooldown=0.0, drain_grace=0.5)
    auto.step()                     # target 2 -> 1, drain a:1
    assert reg.drained == ["a:1"]
    # The victim crashes mid-drain: dynamic-death removes its task.
    a.state = DEAD
    fleet._actual["unified"] = 1
    fleet.dead_nodes.add("replica:0")
    clock[0] = 10.0
    auto.step()
    assert fleet.launched == []     # no spurious replacement
    assert fleet.metrics.get("autoscale_kills") == 1    # reaped as dead
    clock[0] = 20.0
    auto.step()                     # converged: 1 task, target 1
    assert fleet.launched == [] and len(reg.drained) == 1


def test_autoscaler_unkillable_victim_releases_the_drain():
    """A drained victim with no node mapping (malformed beat field, or
    the task vanished) must be RELEASED, not left pinned-DRAINING
    forever — a zombie drain would block convergence and get healthy
    peers drained in its place."""
    noname = _rep("a:1", outstanding=0, node="")     # never advertised
    reg = FakeRegistry([noname, _rep("a:2", outstanding=5,
                                     node="replica:1")])
    fleet = FakeFleet(reg, {"unified": 2})
    clock = [0.0]
    auto = _auto(fleet, {"unified": dict(CALM)}, clock,
                 scale_down_cooldown=0.0, drain_grace=0.5)
    auto.step()                         # drains the least-loaded: a:1
    assert reg.drained == ["a:1"]
    clock[0] = 10.0
    auto.step()                         # flushed, but unkillable
    assert fleet.killed == []
    assert reg.drained == []            # drain released, not zombified
    assert noname.state == ALIVE
    assert fleet.metrics.get("autoscale_kills") == 0
    assert fleet.metrics.get("autoscale_kill_failures") == 1


def test_autoscaler_bounds_clamp_and_never_below_one_alive():
    reg = FakeRegistry([_rep("a:1")])
    fleet = FakeFleet(reg, {"unified": 2}, bounds=(1, 2))
    sig = {"unified": dict(SURGE)}
    clock = [0.0]
    auto = _auto(fleet, sig, clock, scale_up_cooldown=0.0,
                 scale_down_cooldown=0.0)
    auto.step()
    assert fleet.targets["unified"] == 2        # max bound holds
    # Scale-down with only ONE alive member (the other died): target
    # may shrink but the last alive replica is never drained.
    fleet2 = FakeFleet(FakeRegistry([_rep("b:1"),
                                     _rep("b:2", state=WARMING)]),
                       {"unified": 2})
    sig2 = {"unified": dict(CALM)}
    auto2 = _auto(fleet2, sig2, clock, scale_down_cooldown=0.0)
    auto2.step()
    assert fleet2.targets["unified"] == 1
    assert fleet2.registry.drained == []        # invariant held
    # Min bound: target 1 with calm signals stays 1 — never 0.
    fleet3 = FakeFleet(FakeRegistry([_rep("c:1")]), {"unified": 1})
    auto3 = _auto(fleet3, {"unified": dict(CALM)}, clock,
                  scale_down_cooldown=0.0)
    auto3.step()
    assert fleet3.targets["unified"] == 1
    assert fleet3.registry.drained == []


def test_autoscaler_decode_tier_scales_on_kv_headroom():
    reg = FakeRegistry([_rep("d:1", role="decode", node="decode:0"),
                        _rep("d:2", role="decode", node="decode:1")])
    fleet = FakeFleet(reg, {"decode": 2}, bounds=(1, 4))
    sig = {"decode": {"queue_wait_p99_ms": None, "util": 0.0,
                      "kv_headroom": 2.0}}
    clock = [0.0]
    auto = _auto(fleet, sig, clock, scale_up_cooldown=0.0,
                 scale_down_cooldown=0.0, kv_headroom_lo=8.0,
                 kv_headroom_hi=64.0, drain_grace=0.0)
    auto.step()                     # pages exhausted: scale up
    assert fleet.targets["decode"] == 3
    assert fleet.launched and fleet.launched[0][0] == "decode"
    # Plenty of headroom + idle: scale back down.
    sig["decode"] = {"queue_wait_p99_ms": None, "util": 0.0,
                     "kv_headroom": 500.0}
    clock[0] = 100.0
    auto.step()
    assert fleet.targets["decode"] == 2
    assert reg.drained            # a decode replica is draining


def test_autoscaler_converge_relaunches_a_dead_replica():
    """Self-healing rides convergence: a died task (actual < target)
    is relaunched on the next tick even with no signal movement."""
    reg = FakeRegistry([_rep("a:1")])
    fleet = FakeFleet(reg, {"unified": 2})
    fleet._actual["unified"] = 1        # one task died
    auto = _auto(fleet, {"unified": dict(MID)}, [0.0])
    auto.step()
    assert fleet.targets["unified"] == 2        # target untouched
    assert len(fleet.launched) == 1             # replacement launched
    assert fleet.metrics.get("autoscale_launches") == 1


def test_autoscaler_describe_gauge_reports_target_vs_actual():
    reg = FakeRegistry([_rep("a:1"), _rep("a:2", state=WARMING)])
    fleet = FakeFleet(reg, {"unified": 2}, bounds=(1, 8))
    auto = _auto(fleet, {"unified": dict(MID)}, [0.0])
    d = auto.describe()["unified"]
    assert d["target"] == 2 and d["actual"] == 2
    assert d["alive"] == 1 and d["warming"] == 1
    assert d["min"] == 1 and d["max"] == 8
    # Registered as the 'autoscaler' gauge on the fleet's metrics.
    snap = fleet.metrics.snapshot()
    assert snap["gauges"]["autoscaler"]["unified"]["target"] == 2


def test_autoscaler_default_signals_windowed_p99_util_headroom():
    """The real signal source (--autoscale): windowed queue-wait p99
    from cumulative histogram diffs, utilization from heartbeat
    outstanding/capacity, headroom per alive replica."""
    reg = FakeRegistry([
        _rep("a:1", outstanding=3, capacity=4),
        _rep("a:2", outstanding=1, capacity=4),
        _rep("d:1", role="decode", outstanding=0, capacity=4)])
    reg.reps[2].kv_headroom = 40
    fleet = FakeFleet(reg, {"unified": 2, "decode": 1})
    for _ in range(10):
        fleet.metrics.observe("queue_wait_ms", 4.0)
    auto = FleetAutoscaler(fleet, AutoscalerConfig(), clock=lambda: 0.0)
    sig = auto._default_signals()
    assert sig["unified"]["queue_wait_p99_ms"] == 5.0   # bucket edge
    assert sig["unified"]["util"] == pytest.approx(0.5)  # 4 of 8 rows
    assert sig["unified"]["alive"] == 2
    # The NEXT tick only sees the new window's samples.
    for _ in range(5):
        fleet.metrics.observe("queue_wait_ms", 700.0)
    sig2 = auto._default_signals()
    assert sig2["unified"]["queue_wait_p99_ms"] == 1000.0
    # Decode headroom averages over alive members of that tier.
    assert sig2["decode"]["kv_headroom"] == pytest.approx(40.0)
    assert sig2["decode"]["util"] == 0.0


def test_autoscaler_kv_tier_pressure_blocks_down_and_thrash_scales_up():
    """KV-tier occupancy + hit rate are first-class inputs next to
    queue wait: a saturated tier blocks scale-down (the victim's tier
    RAM would evict parked sessions), and saturated + THRASHING — a
    low windowed hit rate says traffic wants what's being evicted —
    arms scale-up even with a calm queue."""
    reg = FakeRegistry([_rep("a:1"), _rep("a:2")])
    fleet = FakeFleet(reg, {"unified": 2}, bounds=(1, 4))
    sig = {"unified": dict(CALM, kv_occupancy=0.95, kv_hit_rate=0.6)}
    clock = [100.0]
    auto = _auto(fleet, sig, clock, scale_up_cooldown=0.0,
                 scale_down_cooldown=0.0)
    auto.step()                     # calm queue, but the tier is full
    assert fleet.targets["unified"] == 2    # down blocked
    assert not reg.drained
    sig["unified"]["kv_hit_rate"] = 0.05    # now thrashing too
    clock[0] = 200.0
    auto.step()
    assert fleet.targets["unified"] == 3    # scale-up armed
    assert fleet.metrics.get("autoscale_up") == 1
    # Tier cool again: calm queue resumes normal scale-down.
    sig["unified"] = dict(CALM, kv_occupancy=0.1, kv_hit_rate=0.9)
    clock[0] = 300.0
    auto.step()
    assert fleet.targets["unified"] == 2
    # Absent signals (no tiered replicas) never block or arm anything.
    sig["unified"] = dict(CALM, kv_occupancy=None, kv_hit_rate=None)
    clock[0] = 400.0
    auto.step()
    assert fleet.targets["unified"] == 1


def test_autoscaler_kv_role_tier_stays_pinned():
    """Dedicated KV-role holders emit no queue-wait or utilization
    signal, so the loop would only ever shrink them — and every
    shrink throws away parked copies.  The tier never retargets
    (plain and composite model/kv keys both), but convergence still
    relaunches a crashed holder."""
    reg = FakeRegistry([_rep("k:1", role="kv"), _rep("a:1")])
    fleet = FakeFleet(reg, {"kv": 1, "m/kv": 1, "unified": 1},
                      bounds=(1, 4))
    sig = {"kv": dict(CALM), "m/kv": dict(SURGE), "unified": dict(MID)}
    clock = [100.0]
    auto = _auto(fleet, sig, clock, scale_up_cooldown=0.0,
                 scale_down_cooldown=0.0)
    auto.step()
    assert fleet.targets["kv"] == 1 and fleet.targets["m/kv"] == 1
    assert not reg.drained
    # Crash relaunch (convergence) still covers the pinned tier.
    fleet._actual["kv"] = 0
    clock[0] = 200.0
    auto.step()
    assert ("kv", "kv:0") in fleet.launched
    assert fleet.targets["kv"] == 1


def test_autoscaler_default_signals_kv_occupancy_and_windowed_hit_rate():
    """The real signal source reads the registry's fleet KV aggregate:
    occupancy = used/budget, hit rate windowed across ticks with
    counter deltas clamped at zero (a dying replica's counters leaving
    the aggregate must not read as negative traffic)."""
    agg = {"replicas": 2, "sessions": 4, "ram_bytes_used": 900,
           "ram_bytes": 1000, "hits": 100, "misses": 100}

    class KvRegistry(FakeRegistry):
        def kv_tier_summary(self):
            return dict(agg)

    reg = KvRegistry([_rep("a:1"), _rep("a:2")])
    fleet = FakeFleet(reg, {"unified": 2})
    auto = FleetAutoscaler(fleet, AutoscalerConfig(), clock=lambda: 0.0)
    sig = auto._default_signals()["unified"]
    assert sig["kv_occupancy"] == pytest.approx(0.9)
    # First tick windows from zero — counters start at replica boot,
    # so the lifetime rate IS the first window.
    assert sig["kv_hit_rate"] == pytest.approx(0.5)
    agg.update(hits=130, misses=170)        # +30 hits, +70 misses
    sig = auto._default_signals()["unified"]
    assert sig["kv_hit_rate"] == pytest.approx(0.3)
    # A replica dies; its counters leave the aggregate.  The clamped
    # window reports no traffic, not negative traffic.
    agg.update(replicas=1, hits=60, misses=80, ram_bytes_used=400,
               ram_bytes=500)
    sig = auto._default_signals()["unified"]
    assert sig["kv_hit_rate"] is None
    assert sig["kv_occupancy"] == pytest.approx(0.8)
    # No tiered replicas at all: both signals go silent.
    agg.update(replicas=0)
    sig = auto._default_signals()["unified"]
    assert sig["kv_occupancy"] is None and sig["kv_hit_rate"] is None
    # A registry without the aggregate (plain fleets) is fine too.
    plain = FleetAutoscaler(FakeFleet(FakeRegistry([_rep("a:1")]),
                                      {"unified": 1}),
                            AutoscalerConfig(), clock=lambda: 0.0)
    sig = plain._default_signals()["unified"]
    assert sig["kv_occupancy"] is None and sig["kv_hit_rate"] is None


def test_histogram_delta_percentile_is_windowed():
    h = Histogram()
    for _ in range(100):
        h.observe(5.0)
    prev = h.cumulative()
    assert Histogram.delta_percentile(None, prev, 0.99) == 5.0
    for _ in range(10):
        h.observe(900.0)
    cur = h.cumulative()
    # Lifetime median still sits in the 5ms bucket...
    assert Histogram.delta_percentile(None, cur, 0.50) == 5.0
    # ... but the WINDOW between the two samples holds only the slow
    # observations — the signal the autoscaler must react to.
    assert Histogram.delta_percentile(prev, cur, 0.50) == 1000.0
    # An empty window yields None, never a stale number.
    assert Histogram.delta_percentile(cur, cur, 0.99) is None


def test_histogram_delta_percentile_edge_cases():
    """The autoscaler's key signal, exercised directly at its edges
    (until now these paths were only hit indirectly through autoscaler
    tests)."""
    # Empty histogram: cumulative is the zero state, every percentile
    # of it is None.
    h = Histogram()
    cur = h.cumulative()
    assert cur[2] == 0
    assert Histogram.delta_percentile(None, cur, 0.99) is None
    # Single-bucket histogram (inf only): every rank lands in the inf
    # bucket — the last finite edge defaults to 0.0 without an
    # inf_value, and inf_value (the tracked max) wins when supplied.
    hb = Histogram(buckets=(float("inf"),))
    hb.observe(123.0)
    cur = hb.cumulative()
    assert Histogram.delta_percentile(None, cur, 0.99) == 0.0
    assert Histogram.delta_percentile(None, cur, 0.99,
                                      inf_value=123.0) == 123.0
    # All observations beyond the last finite edge ("all-inf"): the
    # rank walk must terminate and report the last finite edge (the
    # honest "at least this much" answer), not raise or return inf.
    h2 = Histogram(buckets=(1.0, float("inf")))
    for _ in range(10):
        h2.observe(50.0)
    cur2 = h2.cumulative()
    assert Histogram.delta_percentile(None, cur2, 0.5) == 1.0
    assert Histogram.delta_percentile(None, cur2, 0.5,
                                      inf_value=50.0) == 50.0
    # Window wrap: a prev sample whose BUCKETS differ (a histogram
    # replaced between ticks) cannot be subtracted — the delta falls
    # back to since-birth of cur rather than producing negative
    # counts.
    other = Histogram(buckets=(2.0, float("inf")))
    other.observe(1.0)
    assert Histogram.delta_percentile(other.cumulative(), cur2,
                                      0.5) == 1.0
    # A prev ahead of cur in count with EQUAL buckets (a reset/wrapped
    # window) yields an empty-or-negative total -> None, never a bogus
    # percentile.
    h3 = Histogram(buckets=(1.0, float("inf")))
    h3.observe(0.5)
    assert Histogram.delta_percentile(cur2, h3.cumulative(), 0.5) is None


def test_histogram_cumulative_snapshot_is_immutable_and_consistent():
    h = Histogram(buckets=(1.0, 10.0, float("inf")))
    for v in (0.5, 5.0, 100.0):
        h.observe(v)
    buckets, counts, count = h.cumulative()
    assert buckets == (1.0, 10.0, float("inf"))
    assert counts == (1, 1, 1)
    assert count == 3
    # The snapshot is a value, not a view: later observations must not
    # mutate an already-taken sample (the autoscaler stores prev
    # across ticks).
    h.observe(0.1)
    assert counts == (1, 1, 1)
    # NaN observations are dropped entirely (they would shift every
    # rank while landing in no bucket).
    h.observe(float("nan"))
    assert h.cumulative()[2] == 4


# -- the tox-lint smoke: stub replicas, real registry/router, no JAX --------


def test_autoscaler_smoke_scaleup_warming_routable_scaledown():
    """The jax-free autoscaler smoke: fake-signal scale-up launches a
    stub replica that registers WARMING (invisible to routing), flips
    alive (routable), then fake-signal decay drains it (pinned: its own
    alive beats must not revive it) and kills it BY NODE ID."""
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=1.0, dead_after=2.0,
                          evict_after=10.0, sweep_interval=0.05).start()
    metrics = FleetMetrics()
    router = Router(reg, metrics, token=token)
    servers = {}

    class StubFleet:
        registry = reg
        targets = {"unified": 1}
        scale_lock = threading.RLock()

        def __init__(self):
            self.metrics = metrics
            self._n = 0

        def set_target(self, role, n):
            self.targets[role] = n

        def bounds(self, role):
            return (1, 3)

        def launch_replica(self, role, weights_version=None):
            node = f"replica:{self._n}"
            self._n += 1
            srv = ReplicaServer(
                lambda m, r: r({"op": "completion"}), token=token,
                capacity=4, registry_addr=reg.addr,
                heartbeat_interval=0.05, status=WARMING,
                extra_info=(lambda n: lambda: {"node": n})(node))
            servers[node] = srv.start()
            return node

        def kill_replica(self, node):
            srv = servers.pop(node, None)
            if srv is not None:
                srv.stop()      # heartbeat conn EOF == process death
            return srv is not None

        def tier_actual(self, role):
            return len(servers)

    fleet = StubFleet()
    try:
        base = fleet.launch_replica("unified")      # the boot replica
        servers[base].set_status(None)
        assert _wait(lambda: len(reg.alive()) == 1)
        base_addr = reg.alive()[0].addr
        sig = {"unified": dict(SURGE)}
        auto = FleetAutoscaler(
            fleet, AutoscalerConfig(scale_up_cooldown=0.0,
                                    scale_down_cooldown=0.0,
                                    drain_grace=0.2, drain_timeout=30.0),
            signals=lambda: {k: dict(v) for k, v in sig.items()})
        auto.step()                     # surge -> launch a second stub
        assert fleet.targets["unified"] == 2 and len(servers) == 2
        new_node = next(n for n in servers if n != base)
        assert _wait(lambda: len(reg.warming()) == 1)
        # Warming is NOT routable: every pick lands on the base replica.
        assert router.pick(exclude=(base_addr,)) is None
        servers[new_node].set_status(None)          # "warmup returned"
        assert _wait(lambda: len(reg.alive()) == 2)
        new_addr = next(r.addr for r in reg.alive()
                        if r.addr != base_addr)
        assert _wait(lambda: router.pick(exclude=(base_addr,))
                     == new_addr)
        # Decay: drain + kill, BY NODE ID, without touching the peer.
        sig["unified"] = dict(CALM)
        deadline = time.monotonic() + 30.0
        while len(servers) > 1 and time.monotonic() < deadline:
            auto.step()
            time.sleep(0.05)
        assert set(servers) == {base} or set(servers) == {new_node}
        # The victim's pinned drain held against its own alive beats
        # (it kept heartbeating until the kill): it never re-entered
        # routing, and the survivor is still routable.
        assert _wait(lambda: len(reg.alive()) == 1, timeout=10.0)
        assert metrics.get("autoscale_drains") == 1
        assert metrics.get("autoscale_kills") == 1
    finally:
        for srv in servers.values():
            srv.stop()
        router.close()
        reg.stop()


# -- registry: pinned drain + generation fence ------------------------------


def test_registry_pinned_drain_survives_alive_beat_newer_version_resets():
    """The scale-down drain (begin_drain pinned) must survive the
    victim's own plain alive AND warming beats while it flushes — but a
    relaunch on the same addr advertising a NEWER weights_version must
    reset the stale drain (extends the PR 5 announced_drain cases)."""
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=30.0, dead_after=60.0,
                          sweep_interval=0.05).start()
    try:
        sock = wire.connect(reg.addr)
        wire.send_msg(sock, {"op": "hello", "addr": "p:1",
                             "weights_version": "v1",
                             "outstanding": 2}, token)
        assert _wait(lambda: len(reg.alive()) == 1)
        assert reg.begin_drain("p:1", pinned=True)
        assert [r["state"] for r in reg.snapshot()] == [DRAINING]
        # A plain (routable) beat refreshes liveness but does NOT
        # revive a pinned drain — unlike the replica-announced kind.
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:1",
                             "weights_version": "v1",
                             "outstanding": 0}, token)
        time.sleep(0.2)
        assert [r["state"] for r in reg.snapshot()] == [DRAINING]
        assert reg.alive() == []
        # ... and the beat's fields still landed (flush observability).
        assert reg.members()[0].outstanding == 0
        # A late warming beat cannot revive it either.
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:1",
                             "status": "warming",
                             "weights_version": "v1"}, token)
        time.sleep(0.2)
        assert [r["state"] for r in reg.snapshot()] == [DRAINING]
        # A MALFORMED weights_version (bool is an int subclass) costs
        # the field, never the beat — and must NOT coerce to the label
        # "True" and spuriously reset the pin as a "newer version".
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:1",
                             "weights_version": True}, token)
        time.sleep(0.2)
        assert [r["state"] for r in reg.snapshot()] == [DRAINING]
        assert reg.members()[0].weights_version == "v1"
        # A beat with a NEWER weights_version is a relaunch on a reused
        # addr: the stale drain resets and the entry is routable again.
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:1",
                             "weights_version": "v2"}, token)
        assert _wait(lambda: [r["state"] for r in reg.snapshot()]
                     == [ALIVE])
        assert reg.members()[0].weights_version == "v2"
        assert not reg.members()[0].drain_pinned
        sock.close()
    finally:
        reg.stop()


def test_registry_pinned_drain_dies_with_the_process():
    """DEAD clears the pin exactly like announced_drain: a beat after
    death is a NEW process on the reused addr."""
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=30.0, dead_after=60.0,
                          sweep_interval=0.05).start()
    try:
        sock = wire.connect(reg.addr)
        wire.send_msg(sock, {"op": "hello", "addr": "p:2"}, token)
        assert _wait(lambda: len(reg.alive()) == 1)
        reg.begin_drain("p:2", pinned=True)
        reg.mark_dead("p:2")
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:2",
                             "status": "warming"}, token)
        assert _wait(lambda: [r["state"] for r in reg.snapshot()]
                     == [WARMING])
        wire.send_msg(sock, {"op": "heartbeat", "addr": "p:2"}, token)
        assert _wait(lambda: [r["state"] for r in reg.snapshot()]
                     == [ALIVE])
        sock.close()
    finally:
        reg.stop()


def test_registry_generation_fence_drops_stale_reregistration():
    """After fence_generation(G), beats stamped gen < G — a stalled
    old-generation straggler re-registering after its tier was reaped —
    are dropped whole: the straggler can never serve stale weights."""
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=0.4, dead_after=0.8,
                          evict_after=5.0, sweep_interval=0.05).start()
    try:
        sock = wire.connect(reg.addr)
        wire.send_msg(sock, {"op": "hello", "addr": "g:1", "gen": 0,
                             "weights_version": "v1"}, token)
        assert _wait(lambda: len(reg.alive()) == 1)
        reg.fence_generation(1)
        # The fenced entry's beats no longer land: it goes stale → dead
        # on the sweeper even though the process keeps beating.
        for _ in range(6):
            wire.send_msg(sock, {"op": "heartbeat", "addr": "g:1",
                                 "gen": 0}, token)
            time.sleep(0.2)
        assert _wait(lambda: [r["state"] for r in reg.snapshot()
                              if r["addr"] == "g:1"] in ([DEAD], []),
                     timeout=5.0)
        # Its re-registration (a fresh hello) is dropped too.
        wire.send_msg(sock, {"op": "hello", "addr": "g:1", "gen": 0,
                             "weights_version": "v1"}, token)
        time.sleep(0.3)
        assert not reg.alive()
        # A current-generation hello is untouched by the fence.
        wire.send_msg(sock, {"op": "hello", "addr": "g:2", "gen": 1,
                             "weights_version": "v2"}, token)
        assert _wait(lambda: [r.addr for r in reg.alive()] == ["g:2"])
        # Beats with NO gen (pre-rollout stubs) are never fenced.
        wire.send_msg(sock, {"op": "hello", "addr": "g:3"}, token)
        assert _wait(lambda: len(reg.alive()) == 2)
        sock.close()
    finally:
        reg.stop()


# -- router version preference ----------------------------------------------


def test_router_version_preference_with_fallback():
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=30.0, dead_after=60.0,
                          sweep_interval=0.05).start()
    metrics = FleetMetrics()
    router = Router(reg, metrics, token=token)
    try:
        sock = wire.connect(reg.addr)
        wire.send_msg(sock, {"op": "hello", "addr": "v:1",
                             "weights_version": "v1"}, token)
        wire.send_msg(sock, {"op": "hello", "addr": "v:2",
                             "weights_version": "v2"}, token)
        assert _wait(lambda: len(reg.alive()) == 2)
        # Version-blind by default: both are candidates.
        picks = {router.pick(exclude=(a,)) for a in ("v:1", "v:2")}
        assert picks == {"v:1", "v:2"}
        # The shift: prefer v2 — v1 is never selected while v2 lives.
        router.set_preferred_version("v2")
        for _ in range(8):
            assert router.pick() == "v:2"
        # v2 gone: the old version is the FALLBACK, not an outage.
        reg.mark_dead("v:2")
        assert _wait(lambda: router.pick() == "v:1")
        assert metrics.get("version_fallbacks") >= 1
        router.set_preferred_version(None)
        sock.close()
    finally:
        router.close()
        reg.stop()


# -- gateway rollout op -----------------------------------------------------


def test_gateway_rollout_op_drives_the_control_plane():
    token = wire.new_token()
    reg = ReplicaRegistry(token=token, suspect_after=30.0,
                          dead_after=60.0).start()
    metrics = FleetMetrics()
    router = Router(reg, metrics, token=token)
    gw = Gateway(router, AdmissionController(max_queue=4), metrics,
                 token=token, workers=1).start()
    try:
        client = FleetClient(gw.addr, token, timeout=10.0)
        # No control plane attached: explicit bad_request, never a hang.
        with pytest.raises(RequestFailed) as e:
            client.rollout("v2", timeout=5.0)
        assert e.value.kind == "bad_request"
        calls = []
        gw.rollout_fn = lambda v: (calls.append(v), {"reaped": 3})[1]
        out = client.rollout("v2", timeout=5.0)
        assert out["ok"] and out["weights_version"] == "v2"
        assert out["reaped"] == 3 and calls == ["v2"]
        # A missing version is rejected before the control plane runs.
        with pytest.raises(RequestFailed) as e:
            client.rollout("", timeout=5.0)
        assert e.value.kind == "bad_request"
        # An aborting rollout surfaces as rollout_failed with the cause.
        def boom(v):
            raise RuntimeError("new tier never left warming")
        gw.rollout_fn = boom
        with pytest.raises(RequestFailed) as e:
            client.rollout("v3", timeout=5.0)
        assert e.value.kind == "rollout_failed"
        assert "warming" in str(e.value)
        client.close()
    finally:
        gw.stop()
        reg.stop()


# -- dynamic scheduler ------------------------------------------------------


def test_scheduler_dynamic_add_remove_and_nonfatal_death():
    """Dynamic mode: an empty scheduler starts immediately; add_task
    launches a Mode-B task post-start (served by the per-connection
    rendezvous); remove_task kills it; an uncommanded death is a
    SERVING event (counted, removed from the table) — never fatal."""
    from tfmesos_tpu.backends.local import LocalBackend
    from tfmesos_tpu.scheduler import TPUMesosScheduler

    s = TPUMesosScheduler([], dynamic=True, backend=LocalBackend())
    s.start()
    try:
        assert s.started and s.tasks == []
        t = s.add_task("replica", cmd="sleep 600")
        assert t.dynamic and t.generation == 0
        assert _wait(lambda: t.initialized, timeout=30.0)
        assert s.tasks_of("replica") == [t]
        assert s.task_by_index("replica", 0) is t
        # Commanded removal: table empty, status ignored, not a failure.
        assert s.remove_task(t.id)
        assert _wait(lambda: not s.tasks_of("replica"), timeout=10.0)
        assert s.dynamic_failures.get("replica", 0) == 0
        # Uncommanded death: counted, removed, and NOT cluster-fatal.
        t2 = s.add_task("replica", cmd="exit 3")
        assert t2.task_index == 1           # indices never reuse
        assert _wait(lambda: s.dynamic_failures.get("replica", 0) == 1,
                     timeout=30.0)
        assert not s.tasks_of("replica")
        assert not s.finished()             # no fatal raised
        # Generation bump stamps FUTURE launches only.
        assert s.bump_generation() == 1
        t3 = s.add_task("replica", cmd="sleep 600")
        assert t3.generation == 1
        assert _wait(lambda: t3.initialized, timeout=30.0)
        s.remove_task(t3.id)
    finally:
        s.stop()


def test_scheduler_dynamic_rejects_elastic_and_static_misuse():
    from tfmesos_tpu.scheduler import ClusterError, TPUMesosScheduler
    from tfmesos_tpu.spec import Job

    with pytest.raises(ValueError):
        TPUMesosScheduler([], dynamic=True, restart_policy="elastic")
    with pytest.raises(ValueError):
        TPUMesosScheduler([])               # empty needs dynamic
    s = TPUMesosScheduler([Job(name="w", num=1, cmd="true")])
    with pytest.raises(ClusterError):
        s.add_task("w", cmd="true")         # static schedulers refuse
    with pytest.raises(ClusterError):
        s.remove_task("nope")


# -- FleetServer validation (satellite) -------------------------------------


def test_fleet_server_validation_names_the_offending_values():
    from tfmesos_tpu.fleet.launcher import FleetServer

    with pytest.raises(ValueError, match="replicas=-1"):
        FleetServer(replicas=-1)
    with pytest.raises(ValueError, match="prefill_replicas=1"):
        FleetServer(replicas=1, prefill_replicas=1)
    with pytest.raises(ValueError, match="decode_replicas=2"):
        FleetServer(replicas=0, decode_replicas=2)
    with pytest.raises(ValueError, match="replicas=0"):
        FleetServer(replicas=0)
    with pytest.raises(ValueError, match=r"max_replicas \(2\).*"
                                         r"min_replicas \(3\)"):
        FleetServer(replicas=3, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match=r"count 5.*\[1, 3\]"):
        FleetServer(replicas=5, min_replicas=1, max_replicas=3)
    with pytest.raises(ValueError, match="min_replicas must be >= 1"):
        FleetServer(replicas=1, min_replicas=0, max_replicas=3)
    # Valid autoscale bounds default sanely — and PER TIER: each tier's
    # default ceiling is twice ITS OWN initial count, not the biggest
    # tier's.
    fs = FleetServer(replicas=2, autoscale=True)
    assert (fs.min_replicas, fs.max_replicas) == (1, 4)
    assert fs.bounds("unified") == (1, 4)
    fs2 = FleetServer(replicas=2)
    assert (fs2.min_replicas, fs2.max_replicas) == (1, 2)
    fs3 = FleetServer(replicas=0, prefill_replicas=4, decode_replicas=1,
                      autoscale=True)
    assert fs3.bounds("prefill") == (1, 8)
    assert fs3.bounds("decode") == (1, 2)
    # weights_version joins a shell=True command line: the charset is a
    # security boundary, enforced at the constructor AND at rollout.
    with pytest.raises(ValueError, match="security boundary"):
        FleetServer(replicas=1, weights_version="v2 $(touch /tmp/pwn)")
    with pytest.raises(ValueError, match="security boundary"):
        FleetServer(replicas=1, weights_version="")


# -- end to end on LocalBackend (acceptance) --------------------------------


def _tiny_offline():
    import jax.numpy as jnp

    from tfmesos_tpu.fleet.replica import tiny_model
    from tfmesos_tpu.models import transformer

    cfg, params = tiny_model(seed=0)

    def offline(prompt, max_new_tokens, stop_token=None):
        out = transformer.generate(
            cfg, params, jnp.asarray(np.asarray(prompt, np.int32)[None]),
            max_new_tokens, temperature=0.0, stop_token=stop_token)
        row = np.asarray(out)[0, len(prompt):].tolist()
        if stop_token is not None and stop_token in row:
            row = row[:row.index(stop_token) + 1]
        return row

    return cfg, offline


def _prompts(cfg, n, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size,
                        size=rng.randint(3, 16)).astype(np.int32)
            for _ in range(n)]


@pytest.fixture(scope="module")
def afleet():
    """ONE warmup fleet shared by the two acceptance e2e tests below
    (bring-up compiles are the dominant cost, so both phases — the
    autoscale cycle and the rollout that follows it — ride one fleet;
    the tests are order-dependent by design, like the test_fleet
    module's)."""
    from tfmesos_tpu.fleet.launcher import FleetServer

    fs = FleetServer(replicas=1, rows=2, tiny=True, max_len=64,
                     page_size=16, prefill_bucket=16, warmup=True,
                     weights_version="v1",
                     min_replicas=1, max_replicas=2,
                     request_timeout=300.0, start_timeout=300.0)
    fs.start()
    yield fs
    fs.stop()


def test_fleet_autoscale_end_to_end(afleet):
    """Acceptance: an injected queue-wait surge makes the autoscaler
    launch a replica that registers WARMING (never routed before
    alive) and absorbs load; on signal decay it drains the
    least-loaded replica with ZERO failed or shed in-flight
    requests."""
    fs = afleet
    cfg, offline = _tiny_offline()
    client = fs.client(timeout=300.0)
    prompts = _prompts(cfg, 10, seed=5)
    assert client.generate(prompts[0], 4)["tokens"] == \
        offline(prompts[0], 4)
    base_addr = fs.registry.alive()[0].addr
    sig = {"unified": dict(SURGE)}
    auto = FleetAutoscaler(
        fs, AutoscalerConfig(scale_up_cooldown=0.0,
                             scale_down_cooldown=0.0,
                             drain_grace=0.3, drain_timeout=120.0),
        signals=lambda: {k: dict(v) for k, v in sig.items()})
    auto.step()
    assert fs.targets["unified"] == 2
    assert fs.tier_actual("unified") == 2
    # The newcomer registers WARMING: present, never routable.
    assert _wait(lambda: fs.registry.warming(), timeout=120.0)
    new_addr = fs.registry.warming()[0].addr
    assert new_addr != base_addr
    assert fs.router.pick(exclude=(base_addr,)) is None
    # Requests keep serving correctly through the warmup window.
    assert client.generate(prompts[1], 4)["tokens"] == \
        offline(prompts[1], 4)
    # It flips alive and absorbs load (both replicas carry work).
    assert _wait(lambda: any(r.addr == new_addr
                             for r in fs.registry.alive()),
                 timeout=240.0)
    results = [None] * 10
    errors = []

    def one(i):
        try:
            results[i] = client.generate(prompts[i], 16)
        except Exception as e:
            errors.append((i, e))

    threads = [threading.Thread(target=one, args=(i,))
               for i in range(10)]
    for t in threads:
        t.start()
    both_busy = [False]

    def watch():
        while any(t.is_alive() for t in threads):
            addrs = [r.addr for r in fs.registry.alive()]
            if len(addrs) == 2 and all(
                    fs.router.outstanding(a) > 0 for a in addrs):
                both_busy[0] = True
            time.sleep(0.01)

    watcher = threading.Thread(target=watch)
    watcher.start()
    for t in threads:
        t.join(timeout=300.0)
    watcher.join(timeout=10.0)
    assert not errors, errors
    for i in range(10):
        assert results[i]["tokens"] == offline(prompts[i], 16), \
            f"request {i} diverged on the scaled fleet"
    assert both_busy[0], "the autoscaled replica never took load"
    # Signal decay: drain the least-loaded replica, kill after
    # flush — zero failed, zero shed, nothing in flight dropped.
    sig["unified"] = dict(CALM)
    deadline = time.monotonic() + 120.0
    while fs.tier_actual("unified") > 1 \
            and time.monotonic() < deadline:
        auto.step()
        time.sleep(0.05)
    assert fs.tier_actual("unified") == 1
    assert _wait(lambda: len(fs.registry.alive()) == 1, timeout=30.0)
    # The fleet still serves correctly after the shrink.
    assert client.generate(prompts[2], 4)["tokens"] == \
        offline(prompts[2], 4)
    snap = fs.snapshot()
    c = snap["counters"]
    assert c.get("failed", 0) == 0
    assert c.get("shed_queue", 0) == 0
    assert c.get("autoscale_launches", 0) >= 1
    assert c.get("autoscale_kills", 0) == 1
    gauge = snap["gauges"]["autoscaler"]["unified"]
    assert gauge["target"] == 1 and gauge["actual"] == 1
    roles = snap["gauges"]["roles"]["unified"]
    assert roles["target"] == 1
    client.close()


def test_fleet_rollout_end_to_end(afleet):
    """Acceptance: rollout() to a new weights_version under continuous
    traffic — every request served (no Overloaded, no RoutingError),
    the router never selects an old-version replica after the shift,
    and an old-generation straggler's re-registration is dropped by
    the fence instead of serving stale weights.  Runs on the fleet the
    autoscale test returned to one v1 replica."""
    fs = afleet
    cfg, offline = _tiny_offline()
    client = fs.client(timeout=300.0)
    prompts = _prompts(cfg, 8, seed=7)
    wants = [offline(p, 4) for p in prompts]
    client.generate(prompts[0], 4)          # compile warm
    old_addrs = {r.addr for r in fs.registry.alive()}
    assert all(r.weights_version == "v1" for r in fs.registry.alive())
    stop = threading.Event()
    errors = []
    served = [0]

    def feeder():
        i = 0
        while not stop.is_set():
            try:
                out = client.generate(prompts[i % 8], 4, timeout=300.0)
                assert out["tokens"] == wants[i % 8], \
                    f"request {i} diverged mid-rollout"
            except Exception as e:
                errors.append(e)
                return
            served[0] += 1
            i += 1

    th = threading.Thread(target=feeder)
    th.start()
    time.sleep(0.3)                 # traffic in flight first
    # Drive the rollout through the GATEWAY control op (the
    # tfserve-rollout path), not a direct method call.
    out = client.rollout("v2", timeout=600.0)
    assert out["ok"] and out["new_version"] == "v2"
    assert out["old_version"] == "v1" and out["reaped"] == 1
    stop.set()
    th.join(timeout=300.0)
    assert not errors, f"rollout dropped a request: {errors[0]!r}"
    assert served[0] > 0
    # After the shift: only new-version replicas are routable, and
    # the router cannot select an old-version one.
    alive = fs.registry.alive()
    assert alive and all(r.weights_version == "v2" for r in alive)
    assert not (old_addrs & {r.addr for r in alive})
    for _ in range(8):
        pick = fs.router.pick()
        assert pick not in old_addrs
    # Completions from the new tier stay exact (same seed weights).
    assert client.generate(prompts[0], 4)["tokens"] == wants[0]
    c = fs.snapshot()["counters"]
    assert c.get("failed", 0) == 0 and c.get("shed_queue", 0) == 0
    assert c.get("rollouts", 0) == 1
    # The straggler: a reaped-generation replica re-registering
    # (gen 0 < fence) is DROPPED — stale weights can never serve.
    zombie = wire.connect(fs.registry.addr)
    wire.send_msg(zombie, {"op": "hello", "addr": "zombie:1",
                           "gen": 0, "weights_version": "v1",
                           "role": "unified"}, fs.token)
    time.sleep(0.5)
    assert "zombie:1" not in {r.addr for r in fs.registry.members()}
    # ... while a current-generation hello still lands (the fence,
    # not a closed door, is what blocked the zombie).
    wire.send_msg(zombie, {"op": "hello", "addr": "fresh:1",
                           "gen": out["generation"],
                           "weights_version": "v2"}, fs.token)
    assert _wait(lambda: "fresh:1" in
                 {r.addr for r in fs.registry.members()})
    zombie.close()
    client.close()


@pytest.mark.slow
def test_fleet_rollout_aborts_when_new_tier_never_leaves_warming():
    """Failure mode: the new tier cannot become routable → the rollout
    ABORTS (new tasks reaped, RolloutError), the old version keeps
    serving, and the router preference never shifted."""
    from tfmesos_tpu.fleet.launcher import FleetServer, RolloutError

    cfg, offline = _tiny_offline()
    fs = FleetServer(replicas=1, rows=2, tiny=True, max_len=64,
                     page_size=16, prefill_bucket=16,
                     weights_version="v1",
                     request_timeout=300.0, start_timeout=300.0)
    fs.start()
    try:
        client = fs.client(timeout=300.0)
        prompt = _prompts(cfg, 1, seed=9)[0]
        want = offline(prompt, 4)
        assert client.generate(prompt, 4)["tokens"] == want
        # Sabotage the new tier: an unlaunchable replica cmd.
        real_cmd = fs._replica_cmd

        def broken_cmd(role="unified", weights_version=None, **kw):
            if weights_version == "v2":
                return "exit 7"
            return real_cmd(role, weights_version, **kw)

        fs._replica_cmd = broken_cmd
        with pytest.raises(RolloutError, match="aborted"):
            fs.rollout("v2", warm_timeout=5.0, bake_s=0.0)
        fs._replica_cmd = real_cmd
        # No downtime: the old tier never stopped serving, the version
        # never shifted, and the failed tasks were reaped.
        assert fs.weights_version == "v1"
        assert fs.router._preferred_version is None
        assert _wait(lambda: fs.tier_actual("unified") == 1,
                     timeout=30.0)
        assert client.generate(prompt, 4)["tokens"] == want
        assert fs.snapshot()["counters"].get("rollouts_aborted") == 1
        client.close()
    finally:
        fs.stop()
