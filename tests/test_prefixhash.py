"""Chunk-chain hashing (tfmesos_tpu/prefixhash.py): the jax-free
contract shared by the serving prefix cache and the fleet router's
prefix-affinity matcher.  The chain property — digest j commits to every
token in chunks 0..j — is what makes a replica's advertised digest set
sufficient for longest-prefix matching at the gateway."""

import numpy as np
import pytest

from tfmesos_tpu.prefixhash import (chunk_digest, match_depth,
                                    prompt_digests, token_bytes)


def test_chain_commits_to_every_earlier_token():
    a = np.arange(64, dtype=np.int32)
    d1 = prompt_digests(a, 16)
    assert len(d1) == 4
    # Same leading chunks -> same leading digests; a one-token change in
    # chunk 0 changes EVERY digest after it.
    b = a.copy()
    b[3] += 1
    d2 = prompt_digests(b, 16)
    assert all(x != y for x, y in zip(d1, d2))
    # A change in chunk 2 leaves chunks 0-1 shared.
    c = a.copy()
    c[40] += 1
    d3 = prompt_digests(c, 16)
    assert d3[:2] == d1[:2] and d3[2] != d1[2] and d3[3] != d1[3]


def test_partial_chunks_are_dropped():
    a = np.arange(40, dtype=np.int32)
    assert len(prompt_digests(a, 16)) == 2      # 40 = 2 full + 8 partial
    assert len(prompt_digests(a[:15], 16)) == 0


def test_first_chunk_width_and_seed_shift_the_grid():
    """A constant prefix tail of ``off`` tokens narrows chunk 0 to
    ``page - off`` and seeds the chain — the batcher and the gateway
    must land on identical digests for the same effective stream."""
    page = 16
    tail = np.arange(1000, 1005, dtype=np.int32)        # off = 5
    prompt = np.arange(64, dtype=np.int32)
    seed = chunk_digest(b"", tail)
    d = prompt_digests(prompt, page, first=page - 5, seed=seed)
    # Manual chain: chunk 0 = tail + prompt[:11] worth of positions.
    h = chunk_digest(seed, prompt[:11])
    assert d[0] == h
    assert d[1] == chunk_digest(h, prompt[11:27])
    # Without the seed the chain is different from position 0.
    assert prompt_digests(prompt, page, first=page - 5)[0] != d[0]


def test_match_depth_longest_leading_run():
    a = np.arange(64, dtype=np.int32)
    d = prompt_digests(a, 16)
    adv = {x.hex() for x in d[:3]}
    assert match_depth(d, adv) == 3
    assert match_depth(d, set()) == 0
    assert match_depth(d, {d[1].hex()}) == 0    # no leading run
    assert match_depth(d, [x.hex() for x in d]) == 4
    assert match_depth(d, d[:2]) == 2           # raw bytes accepted too


def test_token_bytes_canonical_across_dtypes():
    assert token_bytes([1, 2, 3]) == token_bytes(
        np.asarray([1, 2, 3], np.int64))
    assert token_bytes(np.asarray([1, 2, 3], np.int32)[::-1][::-1]) == \
        token_bytes([1, 2, 3])


def test_bad_page_rejected():
    with pytest.raises(ValueError):
        prompt_digests([1, 2, 3], 0)
