import threading
import time

import pytest

from tfmesos_tpu import wire
from tfmesos_tpu.backends import FOREVER, ResourceBackend
from tfmesos_tpu.scheduler import ClusterError, MAX_FAILURE_COUNT, TPUMesosScheduler
from tfmesos_tpu.spec import Job, Offer, TaskStatus


class FakeBackend(ResourceBackend):
    """Records scheduler decisions; optionally simulates the task side."""

    def __init__(self, handshake=False):
        self.launched = []
        self.declined = []
        self.suppress_count = 0
        self.revive_count = 0
        self.killed = []
        self.handshake = handshake
        self.scheduler = None
        self.threads = []

    def start(self, scheduler):
        self.scheduler = scheduler
        scheduler.on_registered({"backend": "fake"})

    def stop(self):
        pass

    def launch(self, offer, task_infos):
        self.launched.append((offer.id, [i["task_id"]["value"] for i in task_infos]))
        if self.handshake:
            for info in task_infos:
                t = threading.Thread(target=_fake_task, daemon=True,
                                     args=(info, self.scheduler.addr,
                                           self.scheduler.token, self))
                t.start()
                self.threads.append(t)

    def decline(self, offer, refuse_seconds=5.0):
        self.declined.append((offer.id, refuse_seconds))

    def suppress(self):
        self.suppress_count += 1

    def revive(self):
        self.revive_count += 1

    def kill(self, task_id):
        self.killed.append(task_id)


def _fake_task(task_info, addr, token, backend):
    """Simulates the node runtime handshake + Mode A executor."""
    task_id = task_info["task_id"]["value"]
    sock = wire.connect(addr)
    wire.send_msg(sock, {"op": "register", "task_id": task_id,
                         "addr": "127.0.0.1:9999", "coord_port": 8476}, token)
    config = wire.recv_msg(sock, token)
    wire.send_msg(sock, "ok", token)
    if config["cmd"] is not None:
        sock.close()
        time.sleep(0.05)
        backend.scheduler.on_status(TaskStatus(task_id, "TASK_FINISHED"))
        return
    while True:
        msg = wire.recv_msg(sock, token)
        if msg.get("op") == "shutdown":
            return
        if msg.get("op") == "run":
            wire.send_msg(sock, {"op": "result", "call_id": msg["call_id"],
                                 "ok": True, "value": f"rank{config['rank']}"},
                          token)


def _scheduler(jobs, backend=None, **kw):
    backend = backend or FakeBackend()
    s = TPUMesosScheduler(jobs, backend=backend, quiet=True,
                          start_timeout=10.0, **kw)
    s.addr = "127.0.0.1:0"  # offer handling needs a rendezvous addr
    backend.start(s)
    return s, backend


def offer(oid="o1", cpus=8.0, mem=8192.0, chips=0):
    return Offer(id=oid, agent_id=f"agent-{oid}", hostname="h", cpus=cpus,
                 mem=mem, chips=chips)


def test_first_fit_partial_then_complete():
    s, b = _scheduler([Job(name="worker", num=3, cpus=2.0, mem=1024.0)])
    s.on_offers([offer("o1", cpus=5.0, mem=8192)])  # fits 2 of 3
    assert len(b.launched) == 1
    assert len(b.launched[0][1]) == 2
    s.on_offers([offer("o2", cpus=8.0)])
    assert len(b.launched) == 2
    assert sum(len(ids) for _, ids in b.launched) == 3
    # Fully placed: further offers are suppressed + declined forever
    # (reference scheduler.py:229-232).
    s.on_offers([offer("o3")])
    assert b.suppress_count == 1
    assert b.declined[-1] == ("o3", FOREVER)


def test_decline_useless_offer():
    s, b = _scheduler([Job(name="worker", num=1, cpus=4.0, mem=1024)])
    s.on_offers([offer("small", cpus=1.0)])
    assert b.launched == []
    assert b.declined[0][0] == "small"


def test_chips_dimension_respected():
    s, b = _scheduler([Job(name="worker", num=2, cpus=1.0, mem=100, chips=4)])
    s.on_offers([offer("nochips", chips=0)])
    assert b.launched == []
    s.on_offers([offer("tpu", chips=8)])
    assert len(b.launched[0][1]) == 2


def test_gang_scheduling_all_or_nothing():
    s, b = _scheduler([Job(name="worker", num=4, cpus=2.0, mem=100)],
                      gang_scheduling=True)
    # Batch can only fit 2 of 4 → everything declined, nothing launched.
    s.on_offers([offer("o1", cpus=4.0)])
    assert b.launched == []
    assert b.declined
    # Batch fitting all 4 → launch.
    s.on_offers([offer("o2", cpus=4.0), offer("o3", cpus=4.0)])
    assert sum(len(ids) for _, ids in b.launched) == 4


def test_prestart_failure_revives_with_fresh_id():
    s, b = _scheduler([Job(name="worker", num=1, cpus=1.0, mem=100)])
    s.on_offers([offer("o1")])
    old_id = s.tasks[0].id
    s.on_status(TaskStatus(old_id, "TASK_FAILED", message="oom"))
    assert s.tasks[0].id != old_id
    assert not s.tasks[0].offered
    assert b.revive_count == 1


def test_prestart_failure_budget_exhausted():
    s, b = _scheduler([Job(name="worker", num=1, cpus=1.0, mem=100)])
    for _ in range(MAX_FAILURE_COUNT):
        s.on_offers([offer("o")])
        s.on_status(TaskStatus(s.tasks[0].id, "TASK_FAILED"))
    with pytest.raises(ClusterError):
        s.finished()


def test_poststart_failure_is_fatal():
    s, b = _scheduler([Job(name="worker", num=2, cpus=1.0, mem=100)])
    s.on_offers([offer("o")])
    s.started = True
    s.on_status(TaskStatus(s.tasks[0].id, "TASK_KILLED"))
    with pytest.raises(ClusterError):
        s.finished()


def test_finished_any_job_complete():
    # finished() is true when ANY job fully finished — workers done ends the
    # run even though ps tasks never exit (reference scheduler.py:474-477).
    s, b = _scheduler([Job(name="ps", num=1, cpus=1, mem=10),
                       Job(name="worker", num=2, cpus=1, mem=10)])
    s.on_offers([offer("o")])
    s.started = True
    workers = [t for t in s.tasks if t.job_name == "worker"]
    s.on_status(TaskStatus(workers[0].id, "TASK_FINISHED"))
    assert not s.finished()
    s.on_status(TaskStatus(workers[1].id, "TASK_FINISHED"))
    assert s.finished()


def test_agent_lost_prestart_revives():
    s, b = _scheduler([Job(name="worker", num=1, cpus=1, mem=10)])
    s.on_offers([offer("o1")])
    agent = s.tasks[0].agent_id
    s.on_agent_lost(agent)
    assert b.revive_count == 1
    assert not s.tasks[0].offered


def test_full_bringup_run_and_dispatch():
    """End-to-end over real sockets with a simulated task side: rendezvous,
    config broadcast, SPMD dispatch, teardown."""
    backend = FakeBackend(handshake=True)
    s = TPUMesosScheduler([Job(name="worker", num=3, cpus=1.0, mem=10.0)],
                          backend=backend, quiet=True, start_timeout=15.0)

    def feed_offers():
        while not all(t.offered for t in s.tasks):
            if s.addr and s.addr != "127.0.0.1:0":
                s.on_offers([offer("oX", cpus=16.0)])
            time.sleep(0.01)

    feeder = threading.Thread(target=feed_offers, daemon=True)
    feeder.start()
    s.start()
    try:
        assert s.started
        assert len(s.cluster_def["worker"]) == 3
        assert set(s.targets) == {f"/job:worker/task:{i}" for i in range(3)}
        results = s.run_all("tests.whatever:ignored_by_fake")
        assert results == ["rank0", "rank1", "rank2"]
        assert s.run("tests.whatever:ignored_by_fake") == "rank0"
    finally:
        s.stop()


def test_concurrent_offers_and_statuses_race():
    """Backend threads may deliver offers and statuses concurrently; the
    scheduler's task table must stay consistent (each task launched at most
    once per identity, revives produce fresh ids)."""
    s, b = _scheduler([Job(name="worker", num=4, cpus=1.0, mem=10.0)])
    stop = threading.Event()
    errors = []

    def offer_thread():
        i = 0
        while not stop.is_set():
            try:
                s.on_offers([offer(f"o{i}", cpus=2.0)])
            except Exception as e:  # pragma: no cover
                errors.append(e)
            i += 1
            time.sleep(0.0005)

    def failure_thread():
        while not stop.is_set():
            with s._lock:
                # Keep every identity under the fatal threshold so the
                # revive/relaunch race stays live for the whole window.
                offered = [t for t in s.tasks if t.offered and
                           s.task_failure_count.get(
                               f"{t.job_name}:{t.task_index}", 0) < 2]
            for t in offered[:1]:
                try:
                    s.on_status(TaskStatus(t.id, "TASK_FAILED", message="x"))
                except Exception as e:  # pragma: no cover
                    errors.append(e)
            time.sleep(0.001)

    threads = [threading.Thread(target=offer_thread, daemon=True),
               threading.Thread(target=failure_thread, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=2.0)
    assert not errors
    # Every launch's task ids were valid at launch time; the table still has
    # exactly 4 logical tasks.
    assert len(s.tasks) == 4
    launched_ids = [tid for _, ids in b.launched for tid in ids]
    assert len(launched_ids) == len(set(launched_ids))  # no double-launch


def test_slow_launch_does_not_hold_scheduler_lock():
    """backend.launch (an HTTP POST on Mesos, up to 30s) must run outside
    _lock so status processing proceeds concurrently (VERDICT r3 weak #4)."""

    class SlowLaunchBackend(FakeBackend):
        def __init__(self):
            super().__init__()
            self.launch_started = threading.Event()
            self.release = threading.Event()

        def launch(self, offer, task_infos):
            self.launch_started.set()
            assert self.release.wait(10.0), "test hung"
            super().launch(offer, task_infos)

    b = SlowLaunchBackend()
    s, _ = _scheduler([Job(name="worker", num=2, cpus=1.0, mem=100)],
                      backend=b)
    t = threading.Thread(
        target=lambda: s.on_offers([offer("o1", cpus=8.0)]), daemon=True)
    t.start()
    assert b.launch_started.wait(5.0)
    # While launch blocks, a status update must process promptly.
    tid = s.tasks[0].id
    t0 = time.monotonic()
    s.on_status(TaskStatus(tid, "TASK_RUNNING", agent_id="a"))
    elapsed = time.monotonic() - t0
    assert elapsed < 1.0, f"on_status blocked {elapsed:.1f}s behind launch"
    assert s.tasks[0].last_state == "TASK_RUNNING"
    b.release.set()
    t.join(timeout=5.0)
    assert len(b.launched) == 1


def test_local_spawn_failure_exhausts_into_cluster_error(monkeypatch):
    """Persistent Popen failure must surface as TASK_DROPPED and exhaust
    the revive budget into ClusterError — fast, not at start_timeout
    (VERDICT r3 weak #2 for LocalBackend)."""
    import tfmesos_tpu.backends.local as local_mod
    from tfmesos_tpu.backends.local import LocalBackend

    def failing(*a, **k):
        raise OSError(2, "No such file or directory")

    monkeypatch.setattr(local_mod.subprocess, "Popen", failing)
    s = TPUMesosScheduler(
        [Job(name="w", num=1, cpus=0.5, mem=64, cmd="true")],
        backend=LocalBackend(offer_interval=0.02), quiet=True,
        start_timeout=120.0)
    t0 = time.monotonic()
    with pytest.raises(ClusterError, match="failed 3 times"):
        s.start()
    assert time.monotonic() - t0 < 30.0     # << start_timeout
    assert s.task_failure_count["w:0"] == MAX_FAILURE_COUNT
    # Accounting rolled back on every failed spawn.
    assert s.backend._in_use == [0.0, 0.0, 0]


def test_local_spawn_failure_once_recovers_via_revive(monkeypatch):
    """One flaky spawn, then success: the revive path brings the cluster
    up (the LocalBackend analogue of a transiently rejected ACCEPT)."""
    import tfmesos_tpu.backends.local as local_mod
    from tfmesos_tpu.backends.local import LocalBackend

    orig = local_mod.subprocess.Popen
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(2, "No such file or directory")
        return orig(*a, **k)

    monkeypatch.setattr(local_mod.subprocess, "Popen", flaky)
    s = TPUMesosScheduler(
        [Job(name="w", num=1, cpus=0.5, mem=64, cmd="true")],
        backend=LocalBackend(offer_interval=0.02), quiet=True,
        start_timeout=60.0)
    try:
        s.start()
        s.join()
    finally:
        s.stop()
    assert calls["n"] >= 2
    assert s.task_failure_count["w:0"] == 1


def test_mode_b_bringup_and_finish():
    backend = FakeBackend(handshake=True)
    s = TPUMesosScheduler([Job(name="worker", num=2, cpus=1.0, mem=10.0,
                               cmd="echo hi")],
                          backend=backend, quiet=True, start_timeout=15.0)

    def feed_offers():
        while not all(t.offered for t in s.tasks):
            if s.addr and s.addr != "127.0.0.1:0":
                s.on_offers([offer("oY", cpus=16.0)])
            time.sleep(0.01)

    threading.Thread(target=feed_offers, daemon=True).start()
    s.start()
    try:
        deadline = time.time() + 10
        while not s.finished():
            assert time.time() < deadline, "tasks never finished"
            time.sleep(0.02)
    finally:
        s.stop()


def test_token_transport_backend_mismatch_rejected():
    import pytest

    from tfmesos_tpu.backends.local import LocalBackend
    from tfmesos_tpu.spec import Job
    from tfmesos_tpu.scheduler import TPUMesosScheduler

    jobs = [Job(name="w", num=1)]
    with pytest.raises(ValueError, match="colocated"):
        TPUMesosScheduler(jobs, backend=LocalBackend(),
                          token_transport="secret")
    with pytest.raises(ValueError, match="env|file|secret"):
        TPUMesosScheduler(jobs, backend=LocalBackend(),
                          token_transport="carrier-pigeon")


def test_run_on_duplicate_ranks_rejected():
    import pytest

    from tfmesos_tpu import ClusterError, Job, cluster
    from tfmesos_tpu.backends.local import LocalBackend

    with cluster(Job(name="w", num=2, cpus=0.5, mem=64.0),
                 backend=LocalBackend(), quiet=True, start_timeout=60.0,
                 extra_config={"no_jax": True}) as c:
        with pytest.raises(ClusterError, match="duplicate"):
            c.run_on([0, 0], "support_funcs:ping", "x")
        # The rejection happens before any send: the channel stays usable.
        assert [r["rank"] for r in c.run_on([1, 0], "support_funcs:ping", "x")] \
            == [1, 0]


def test_heartbeat_revive_gated_on_offer_flow():
    """The heartbeat revive backstop fires only on EVIDENCE the offer tap
    is closed (advisor r4): while offers keep arriving (e.g. gang
    scheduling's short declines) no revive churns the master's filters;
    a silent heartbeat interval (or a failed revive POST) re-opens."""
    s, b = _scheduler([Job(name="worker", num=1, cpus=4.0, mem=1024)])
    s.on_offers([offer("small", cpus=1.0)])     # declined, task unplaced
    base = b.revive_count
    s.on_heartbeat()                            # offers flowed: no revive
    assert b.revive_count == base
    s.on_heartbeat()                            # silent interval: revive
    assert b.revive_count == base + 1
    s.on_offers([offer("small2", cpus=1.0)])    # flow resumes
    s.on_heartbeat()
    assert b.revive_count == base + 1


def test_launch_dropped_when_task_reset_between_placement_and_launch():
    """Advisor r4: a terminal status on another thread can reset() a
    placed task between TaskInfo rendering (under the lock) and the
    backend.launch call (outside it); the stale launch must be dropped —
    injected deterministically via a decline callback that fires the
    terminal status in the window."""

    class RacingBackend(FakeBackend):
        def decline(self, offer_, refuse_seconds=5.0):
            super().decline(offer_, refuse_seconds)
            if self.scheduler is not None and not self.raced:
                self.raced = True
                # The placed task's CURRENT id — exactly what a reaper
                # thread would report a terminal state for.
                tid = self.scheduler.tasks[0].id
                self.scheduler.on_status(TaskStatus(tid, "TASK_FAILED"))

    backend = RacingBackend()
    backend.raced = False
    s, b = _scheduler([Job(name="worker", num=1, cpus=4.0, mem=1024)],
                      backend=backend)
    # Offer A is useless (declined — the injection point); offer B fits.
    s.on_offers([offer("useless", cpus=1.0), offer("fits", cpus=8.0)])
    assert b.launched == []                     # stale launch dropped
    assert ("fits", 1.0) in b.declined          # offer B given back
    assert not s.tasks[0].offered               # re-queued for placement
    assert s.task_failure_count == {"worker:0": 1}  # the injected failure
    # The next good offer launches under the task's FRESH id.
    s.on_offers([offer("retry", cpus=8.0)])
    assert len(b.launched) == 1
    assert b.launched[0][1] == [s.tasks[0].id]
