"""zk:// master resolution against a fake ZooKeeper server speaking the
same minimal jute frames the client sends (connect, getChildren, getData)."""

import json
import socket
import struct
import threading

import pytest

from tfmesos_tpu.backends.zk import parse_zk_url, resolve_master


class FakeZK:
    """Single-connection fake ensemble with Mesos master znodes."""

    def __init__(self, znodes):
        self.znodes = znodes  # {name: data-bytes}
        self.requests = []
        self.server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.server.bind(("127.0.0.1", 0))
        self.server.listen(4)
        self.port = self.server.getsockname()[1]
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _read_frame(self, conn):
        hdr = b""
        while len(hdr) < 4:
            chunk = conn.recv(4 - len(hdr))
            if not chunk:
                return None
            hdr += chunk
        (n,) = struct.unpack(">i", hdr)
        data = b""
        while len(data) < n:
            data += conn.recv(n - len(data))
        return data

    def _send_frame(self, conn, payload):
        conn.sendall(struct.pack(">i", len(payload)) + payload)

    def _serve(self):
        while True:
            try:
                conn, _ = self.server.accept()
            except OSError:
                return
            with conn:
                # ConnectRequest -> ConnectResponse
                req = self._read_frame(conn)
                if req is None:
                    continue
                self._send_frame(
                    conn, struct.pack(">iiq", 0, 10000, 1)
                    + struct.pack(">i", 16) + b"\x00" * 16 + b"\x00")
                while True:
                    frame = self._read_frame(conn)
                    if frame is None:
                        break
                    xid, op = struct.unpack(">ii", frame[:8])
                    (plen,) = struct.unpack(">i", frame[8:12])
                    path = frame[12:12 + plen].decode()
                    self.requests.append((op, path))
                    header = struct.pack(">iqi", xid, 1, 0)
                    if op == 8:  # getChildren
                        names = sorted(self.znodes)
                        body = struct.pack(">i", len(names))
                        for n in names:
                            body += struct.pack(">i", len(n)) + n.encode()
                        self._send_frame(conn, header + body)
                    elif op == 4:  # getData
                        name = path.rsplit("/", 1)[1]
                        data = self.znodes.get(name)
                        if data is None:
                            self._send_frame(
                                conn, struct.pack(">iqi", xid, 1, -101))
                        else:
                            self._send_frame(
                                conn, header + struct.pack(">i", len(data))
                                + data)

    def close(self):
        self.server.close()


def _master_znode(ip, port):
    return json.dumps({"address": {"ip": ip, "port": port},
                       "hostname": ip}).encode()


def test_parse_zk_url_forms():
    servers, path = parse_zk_url("zk://a:2181,b:2182/mesos")
    assert servers == [("a", 2181), ("b", 2182)]
    assert path == "/mesos"
    servers, path = parse_zk_url("zk://user:pw@a/mesos/sub/")
    assert servers == [("a", 2181)]
    assert path == "/mesos/sub"
    with pytest.raises(ValueError):
        parse_zk_url("zk://a:2181")  # no path
    with pytest.raises(ValueError):
        parse_zk_url("http://a:2181/mesos")


def test_resolve_master_picks_lowest_sequence():
    zk = FakeZK({
        "json.info_0000000007": _master_znode("10.0.0.7", 5051),
        "json.info_0000000003": _master_znode("10.0.0.3", 5050),
        "log_replicas": b"not-a-master",  # non-master znode ignored
    })
    try:
        master = resolve_master(f"zk://127.0.0.1:{zk.port}/mesos")
        assert master == "10.0.0.3:5050"  # lowest sequence = leader
        assert (8, "/mesos") in zk.requests
        assert (4, "/mesos/json.info_0000000003") in zk.requests
    finally:
        zk.close()


def test_resolve_master_falls_through_dead_servers():
    zk = FakeZK({"json.info_0000000001": _master_znode("10.1.1.1", 5050)})
    try:
        # First ensemble member unreachable; second answers.
        master = resolve_master(
            f"zk://127.0.0.1:1,127.0.0.1:{zk.port}/mesos")
        assert master == "10.1.1.1:5050"
    finally:
        zk.close()


def test_resolve_master_no_masters_registered():
    zk = FakeZK({"log_replicas": b"x"})
    try:
        with pytest.raises(IOError, match="json.info"):
            resolve_master(f"zk://127.0.0.1:{zk.port}/mesos")
    finally:
        zk.close()


def test_mesos_backend_accepts_zk_master():
    """End-to-end: MesosBackend(zk://...) resolves the leader address."""
    from tfmesos_tpu.backends.mesos import MesosBackend

    zk = FakeZK({"json.info_0000000002": _master_znode("10.9.9.9", 5055)})
    try:
        backend = MesosBackend(f"zk://127.0.0.1:{zk.port}/mesos")
        assert (backend.host, backend.port) == ("10.9.9.9", 5055)
    finally:
        zk.close()
