"""Fused head+cross-entropy (ops/layers.fused_linear_cross_entropy):
chunked loss/grads must match the materialize-the-logits reference exactly
(same fp32 reduction math, different grouping), and the transformer's
loss_fn must auto-select it only where it is the right call.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfmesos_tpu.compat import shard_map
from tfmesos_tpu.models import transformer
from tfmesos_tpu.ops.layers import cross_entropy_loss, fused_linear_cross_entropy
from tfmesos_tpu.parallel.mesh import build_mesh


def _ref_loss(x, w, labels, z_loss=0.0):
    logits = x @ w.astype(x.dtype)
    return cross_entropy_loss(logits, labels, z_loss=z_loss)


@pytest.mark.parametrize("z_loss", [0.0, 1e-3])
@pytest.mark.parametrize("chunk", [7, 16, 1000])
def test_fused_ce_matches_reference_loss_and_grads(z_loss, chunk):
    d, v = 16, 37
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 8, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (3, 8), 0, v)

    ref, (dx_ref, dw_ref) = jax.value_and_grad(_ref_loss, argnums=(0, 1))(
        x, w, labels, z_loss)
    got, (dx, dw) = jax.value_and_grad(
        lambda x_, w_: fused_linear_cross_entropy(x_, w_, labels, z_loss,
                                                  chunk),
        argnums=(0, 1))(x, w)

    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-5, atol=1e-6)


def test_fused_ce_bf16_inputs_fp32_master_weight():
    """The model path: bf16 hidden states, fp32 master head — compute runs
    in bf16 (weight cast at the matmul, as the standard path does) but dw
    accumulates fp32 and returns at the master dtype."""
    d, v = 32, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 6, d)).astype(jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.2
    labels = jax.random.randint(jax.random.PRNGKey(2), (4, 6), 0, v)

    ref, (dx_ref, dw_ref) = jax.value_and_grad(_ref_loss, argnums=(0, 1))(
        x, w, labels)
    got, (dx, dw) = jax.value_and_grad(
        lambda x_, w_: fused_linear_cross_entropy(x_, w_, labels),
        argnums=(0, 1))(x, w)

    assert dx.dtype == jnp.bfloat16 and dw.dtype == jnp.float32
    np.testing.assert_allclose(float(got), float(ref), rtol=5e-3)
    np.testing.assert_allclose(np.asarray(dx, dtype=np.float32),
                               np.asarray(dx_ref, dtype=np.float32),
                               rtol=0.1, atol=5e-4)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=0.1, atol=5e-4)


TINY = transformer.TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    max_seq_len=32, dtype=jnp.float32)


def test_loss_fn_fused_matches_standard():
    params = transformer.init_params(TINY, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                TINY.vocab_size)
    batch = {"tokens": tokens}
    import dataclasses
    fused_cfg = dataclasses.replace(TINY, fused_ce=True, ce_chunk=8)
    plain_cfg = dataclasses.replace(TINY, fused_ce=False)

    l_fused, (g_fused,) = jax.value_and_grad(
        lambda p: transformer.loss_fn(fused_cfg, p, batch)[0], argnums=(0,))(
        params)
    l_plain, (g_plain,) = jax.value_and_grad(
        lambda p: transformer.loss_fn(plain_cfg, p, batch)[0], argnums=(0,))(
        params)

    np.testing.assert_allclose(float(l_fused), float(l_plain), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_fused),
                    jax.tree_util.tree_leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=1e-6)


def test_fused_ce_mode_auto_selection():
    params = transformer.init_params(TINY, jax.random.PRNGKey(0))
    mode = transformer._fused_ce_mode
    assert mode(TINY, params, None) == "dense"
    # Multi-device data-only meshes take the batch-sharded path: the dense
    # chunking would cut every chunk across the dp sharding.  The shard_map
    # needs the batch to divide over the data axes — indivisible (or
    # unknown) batches keep the GSPMD dense route.
    assert mode(TINY, params, build_mesh({"dp": 8}), batch_size=8) == "dp"
    assert mode(TINY, params, build_mesh({"dp": 8}), batch_size=6) == "dense"
    assert mode(TINY, params, build_mesh({"dp": 8})) == "dense"
    assert mode(TINY, params, build_mesh({"dp": 4, "fsdp": 2}),
                batch_size=16) == "dp"
    assert mode(TINY, params, build_mesh({"dp": 4, "tp": 2})) == "tp"
    assert mode(TINY, params, build_mesh({"sp": 8})) is None
    assert mode(TINY, params, build_mesh({"pp": 2, "dp": 4})) is None
    # Size-1 axes don't count: a degenerate tp axis is still data-only.
    assert mode(TINY, params, build_mesh({"dp": 8, "tp": 1}),
                batch_size=8) == "dp"
    qparams = transformer.quantize_params(TINY, params)
    assert mode(TINY, qparams, None) is None


def test_loss_fn_tp_mesh_matches_single_device():
    """The vocab-parallel path through loss_fn: loss AND grads on a
    dp x tp mesh must match the meshless (fused-dense) run."""
    mesh = build_mesh({"dp": 4, "tp": 2})
    params = transformer.init_params(TINY, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                TINY.vocab_size)
    batch = {"tokens": tokens}
    assert transformer._fused_ce_mode(TINY, params, mesh) == "tp"

    ref, g_ref = jax.value_and_grad(
        lambda p: transformer.loss_fn(TINY, p, batch)[0])(params)
    got, g = jax.jit(jax.value_and_grad(
        lambda p: transformer.loss_fn(TINY, p, batch, mesh)[0]))(params)

    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g)[0],
            jax.tree_util.tree_flatten_with_path(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5, err_msg=str(pa))


@pytest.mark.parametrize("axes", [{"tp": 8}, {"dp": 2, "tp": 4},
                                  {"dp": 2, "fsdp": 2, "tp": 2}])
def test_vocab_parallel_ce_matches_reference(axes):
    from tfmesos_tpu.ops.layers import vocab_parallel_cross_entropy
    mesh = build_mesh(axes)
    d, v = 16, 64
    nb = axes.get("dp", 1) * axes.get("fsdp", 1)
    x = jax.random.normal(jax.random.PRNGKey(0), (2 * nb, 8, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (2 * nb, 8), 0, v)

    ref, (dx_ref, dw_ref) = jax.value_and_grad(_ref_loss, argnums=(0, 1))(
        x, w, labels, 1e-3)
    got, (dx, dw) = jax.jit(jax.value_and_grad(
        lambda x_, w_: vocab_parallel_cross_entropy(
            x_, w_, labels, mesh, z_loss=1e-3, chunk=8),
        argnums=(0, 1)))(x, w)

    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-5, atol=1e-6)


def test_vocab_parallel_ce_through_trainer_machinery():
    """The tp fused-CE path composed with the full trainer stack:
    make_train_step with steps_per_call > 1 AND grad_accum > 1 on a
    dp x tp mesh must train (finite, decreasing-ish loss) — custom VJPs
    inside shard_maps inside scan inside scan inside jit."""
    import optax

    from tfmesos_tpu.train.trainer import make_train_step

    mesh = build_mesh({"dp": 4, "tp": 2})
    params = transformer.init_params(TINY, jax.random.PRNGKey(0))
    assert transformer._fused_ce_mode(TINY, params, mesh) == "tp"
    opt = optax.adamw(3e-3)
    step = make_train_step(
        lambda p, b: transformer.loss_fn(TINY, p, b, mesh), opt, mesh=mesh,
        param_specs=transformer.partition_specs(TINY, mesh),
        steps_per_call=2, grad_accum=2)
    params, opt_state = step.place(params, opt.init(params))

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(6):
        batch = {"tokens": rng.randint(0, TINY.vocab_size,
                                       size=(2, 8, 17)).astype(np.int32)}
        params, opt_state, metrics = step(params, opt_state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


@pytest.mark.parametrize("axes", [{"dp": 8}, {"dp": 2, "fsdp": 4}])
def test_dp_fused_ce_matches_reference(axes):
    """The batch-sharded fused CE: loss AND grads on data-parallel meshes
    must match the materialize-the-logits reference."""
    from tfmesos_tpu.ops.layers import data_parallel_fused_cross_entropy
    mesh = build_mesh(axes)
    d, v = 16, 64
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 8, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (16, 8), 0, v)

    ref, (dx_ref, dw_ref) = jax.value_and_grad(_ref_loss, argnums=(0, 1))(
        x, w, labels, 1e-3)
    got, (dx, dw) = jax.jit(jax.value_and_grad(
        lambda x_, w_: data_parallel_fused_cross_entropy(
            x_, w_, labels, mesh, 1e-3, 8),
        argnums=(0, 1)))(x, w)

    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-5, atol=1e-6)


def test_fused_ce_on_dp_mesh_matches_single_device():
    """loss_fn's auto "dp" route end to end: loss and grads on a dp mesh
    must match the meshless (fused-dense) run."""
    mesh = build_mesh({"dp": 8})
    params = transformer.init_params(TINY, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                TINY.vocab_size)
    batch = {"tokens": tokens}
    assert transformer._fused_ce_mode(TINY, params, mesh,
                                      batch_size=8) == "dp"
    ref, g_ref = jax.value_and_grad(
        lambda p: transformer.loss_fn(TINY, p, batch)[0])(params)
    got, g = jax.jit(jax.value_and_grad(
        lambda p: transformer.loss_fn(TINY, p, batch, mesh)[0]))(params)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(g)[0],
            jax.tree_util.tree_flatten_with_path(g_ref)[0]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5, err_msg=str(pa))


@pytest.mark.parametrize("z_loss", [0.0, 1e-3])
def test_vocab_parallel_ce_inbody_matches_reference(z_loss):
    """The in-body vocab-parallel CE (the 1F1B loss tail): called INSIDE
    a shard_map with the head vocab-sharded, loss and in-body-vjp grads
    must match the dense reference."""
    from jax.sharding import PartitionSpec as P

    from tfmesos_tpu.ops.layers import vocab_parallel_ce_inbody

    d, v = 16, 64
    mesh = build_mesh({"tp": 8})
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 8, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(1), (d, v), jnp.float32) * 0.3
    labels = jax.random.randint(jax.random.PRNGKey(2), (3, 8), 0, v)

    ref, (dx_ref, dw_ref) = jax.value_and_grad(
        _ref_loss, argnums=(0, 1))(x, w, labels, z_loss)

    def local(xl, wl, ll):
        # In-body vjp, exactly as the 1F1B backward runs it.
        loss, vjp = jax.vjp(
            lambda x_, w_: vocab_parallel_ce_inbody(x_, w_, ll, "tp",
                                                    z_loss, 16), xl, wl)
        dx, dw = vjp(jnp.ones((), jnp.float32))
        return loss, dx, dw

    loss, dx, dw = shard_map(
        local, mesh=mesh, in_specs=(P(), P(None, "tp"), P()),
        out_specs=(P(), P(), P(None, "tp")), check_vma=False)(x, w, labels)
    np.testing.assert_allclose(float(loss), float(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), np.asarray(dx_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw), np.asarray(dw_ref),
                               rtol=1e-5, atol=1e-6)


def test_lm_z_loss_consistent_across_paths():
    """cfg.z_loss (LM-head logit stabilizer) must produce the same loss on
    the unfused, fused-dense, dp-sharded, and tp vocab-parallel routes,
    and actually move the objective."""
    import dataclasses

    cfg = dataclasses.replace(TINY, z_loss=1e-3)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                TINY.vocab_size)
    batch = {"tokens": tokens}
    base = float(transformer.loss_fn(
        dataclasses.replace(cfg, fused_ce=False), params, batch)[0])
    for mesh in (None, build_mesh({"dp": 8}), build_mesh({"dp": 4, "tp": 2})):
        got = float(jax.jit(lambda p, b, m=mesh: transformer.loss_fn(
            cfg, p, b, m)[0])(params, batch))
        np.testing.assert_allclose(got, base, rtol=1e-5)
    plain = float(transformer.loss_fn(
        dataclasses.replace(cfg, z_loss=0.0), params, batch)[0])
    assert base > plain
